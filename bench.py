"""Benchmark harness: the full BASELINE.md config matrix on real hardware.

Prints exactly ONE JSON line (driver contract).  ``--compare`` switches to
the perf-regression gate instead of running benchmarks: the committed
``BENCH_r0*.json`` history (or ``--current``) is checked row-by-row against
the best comparable prior round (quest_tpu/obs/regress.py; the CI
``bench-regress`` job) and the process exits nonzero on any gating
regression past tolerance.

The headline metric is the 24q random-circuit f32 fused throughput; the
``matrix`` field carries every BASELINE.md config measured in the same run:

  - random 24q: f32/f64 x fused/unfused  (single-chip hot path)
  - 20q Clifford+T statevector           (BASELINE config 2)
  - 14q density matrix, mixDamping + mixDepolarising per layer (config 4)
  - 28q QFT                              (config 5's diagonal/swap path)
  - 22q QFT on an 8-virtual-device CPU mesh (cross-shard diagonal + swap
    routing end-to-end — communication-pattern validation, config 5's
    distributed regime without multi-chip hardware)
  - scheduled-vs-unscheduled pairs on the same mesh (22q QFT, 24q random):
    the comm-aware scheduler's predicted and measured comm deltas

Workloads run INSIDE one jitted program (lax.fori_loop over layers where
applicable) so remote-dispatch latency cannot pollute the measurement; a
scalar norm readback bounds each timing.

Metric: single-qubit-gate amplitude updates / sec / chip — value =
state_size * gates / wall_seconds (BASELINE.md north star >= 1e8).

Env overrides: QUEST_BENCH_QUBITS / DEPTH / PRECISION / FUSE configure the
headline; QUEST_BENCH_MATRIX=0 skips the extra configs.
"""

from __future__ import annotations

import json
import os
import sys
import time

# must precede any jax import: the sharded-QFT config builds an 8-device CPU
# mesh alongside the TPU backend
_N_VIRT = 8
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={_N_VIRT}").strip()

BASELINE_AMPS_PER_SEC = 1e8  # driver target (BASELINE.md north star)

HBM_PEAK_BYTES_PER_SEC = 819e9  # v5e HBM bandwidth (public spec ~819 GB/s)

_PROVENANCE: dict | None = None


def _provenance() -> dict:
    """Environment provenance stamped onto every emitted row so the
    BENCH_r0*.json trajectories are self-describing: a number is only
    comparable to another number when the software stack that produced it
    is known (jax/jaxlib/libtpu versions, git sha, backend platform)."""
    global _PROVENANCE
    if _PROVENANCE is not None:
        return _PROVENANCE
    import platform as _plat
    import subprocess

    import jax
    import numpy as np
    prov = {
        "jax": jax.__version__,
        "numpy": np.__version__,
        "python": _plat.python_version(),
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", ""),
    }
    try:
        import jaxlib
        prov["jaxlib"] = jaxlib.__version__
    except Exception:
        pass
    try:
        import libtpu
        prov["libtpu"] = getattr(libtpu, "__version__", "present")
    except Exception:
        pass
    try:
        prov["git_sha"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10).stdout.strip()
    except Exception:
        prov["git_sha"] = ""
    try:
        # which cost-model constants this round's model columns used
        # (obs/calibrate.py): the fitted profile's id, or "default"
        from quest_tpu.obs import active_profile
        prof = active_profile()
        prov["calibration"] = "default" if prof is None else prof.profile_id
    except Exception:
        prov["calibration"] = "default"
    _PROVENANCE = prov
    return prov


def _roofline(num_amps: int, precision: int, passes: float,
              seconds: float) -> dict:
    """Achieved-HBM-bandwidth fields making each number auditable as
    'N passes x state size at X% of peak'.  A pass is modeled as one full
    read + one full write of the state (gather partners / matmul temps add
    unmodeled traffic, so the true fraction is >= the reported one).
    ``num_amps`` is the stored amplitude count (2^n, or 4^n for a density
    matrix); the SoA pair stores 8 (f32) / 16 (f64) bytes per amplitude."""
    state_bytes = num_amps * 2 * (4 if precision == 1 else 8)
    traffic = 2.0 * state_bytes * passes
    gbps = traffic / max(seconds, 1e-9) / 1e9
    return {"hbm_passes": passes,
            "state_bytes": state_bytes,
            "hbm_gb_per_sec": round(gbps, 2),
            "hbm_peak_frac": round(gbps * 1e9 / HBM_PEAK_BYTES_PER_SEC, 4)}


def _stamp_counters(cfg: dict, compile_seconds: float | None = None) -> dict:
    """Fold the runtime counters (quest_tpu/obs/counters.py) into a row
    config: the compile wall and — where the backend exposes
    ``memory_stats()`` (TPU/GPU; the CPU backend reports none) — the live
    HBM watermark.  ``--compare`` reports compile-time deltas from these
    fields alongside amps/s; it never gates on them."""
    from quest_tpu.obs import update_hbm_watermark
    if compile_seconds is not None:
        cfg["compile_seconds"] = compile_seconds
    wm = update_hbm_watermark()
    if wm is not None:
        cfg["hbm_peak_bytes"] = wm["peak_bytes_in_use"]
        cfg["hbm_bytes_in_use"] = wm["bytes_in_use"]
    return cfg


def _run_layered(ops_apply, state, depth, best_of=1):
    """(compute_seconds, norm, wall, overhead) — best of ``best_of`` timed
    runs of ONE compiled program (retries reuse the jitted function, so the
    only extra cost is the measured seconds; they defend against
    remote-tunnel run-to-run variance, observed up to ~15x on a bad
    window).  The compile+warm wall is kept as
    ``_run_layered.last_compile_seconds`` (the bench.py attribute idiom,
    cf. _run_config.last_exc) and recorded into the runtime counters."""
    import jax
    import jax.numpy as jnp
    from functools import partial
    from quest_tpu.obs import record_compile

    @partial(jax.jit, static_argnames=())
    def run(s, iters):
        def body(_, st):
            return ops_apply(st)
        s = jax.lax.fori_loop(0, iters, body, s)
        return jnp.sum(s[0] * s[0] + s[1] * s[1])

    t0 = time.perf_counter()
    float(run(state, 1))  # compile + warm
    _run_layered.last_compile_seconds = time.perf_counter() - t0
    record_compile(_run_layered.last_compile_seconds)
    dts, overheads = [], []
    total = 0.0
    for _ in range(max(1, best_of)):
        t0 = time.perf_counter()
        base = float(run(state, 0))
        overheads.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        total = float(run(state, depth))
        dts.append(time.perf_counter() - t0)
    # min over dt and overhead INDEPENDENTLY: a noisy overhead probe paired
    # with a fast run would otherwise overstate throughput; this way noise
    # can only make the reported number pessimistic
    dt = min(dts)
    overhead = min(overheads)
    return max(dt - overhead, 1e-9), total, dt, overhead


def bench_random(n, depth, precision, fuse, seed=11, best_of=1):
    """Haar 1q layer + CZ ladder, fused by the native scheduler."""
    import jax.numpy as jnp
    from quest_tpu.circuit import _apply_one, random_circuit

    dtype = jnp.float32 if precision == 1 else jnp.float64
    circuit = random_circuit(n, depth=1, seed=seed)
    if fuse:
        # f64 pack policy: 2-qubit packs route through the gather engine
        # (4 partner moves/pass — measured 1.54x the 7-wide packs' chunked
        # emulated matmuls at 24q).  Wider f64 packs are ALSO blocked by an
        # XLA:TPU X64-rewriter miscompilation: a 3q-pack program computes a
        # wrong norm on-chip while the identical ops pass on CPU (see
        # docs/DESIGN.md "f64 on TPU").  f32 keeps the full 7-qubit MXU
        # packs.
        circuit.optimize(max_pack=7 if precision == 1 else 2)
    ops = circuit.key()

    def layer(s):
        for op in ops:
            s = _apply_one(s, op)
        return s

    state = jnp.zeros((2, 1 << n), dtype=dtype).at[0, 0].set(1.0)
    compute, total, dt, overhead = _run_layered(layer, state, depth,
                                                best_of=best_of)
    assert abs(total - 1.0) < 1e-2, f"state not normalised: {total}"
    value = (1 << n) * n * depth / compute
    cfg = {"qubits": n, "depth": depth, "precision": precision,
           "fused": fuse, "ops_per_layer": len(ops),
           "seconds": dt, "overhead_seconds": overhead}
    cfg.update(_roofline(1 << n, precision, len(ops) * depth, compute))
    _stamp_counters(cfg, _run_layered.last_compile_seconds)
    return value, cfg


def bench_random_big30(depth=4, seed=11):
    """30-qubit f32 single-chip random layer — the largest state one 15.75
    GiB chip can hold (8 GiB) — via the IN-PLACE Pallas whole-layer engine
    (ops/pallas_layer.py apply_1q_layer_planes: input_output_aliases keeps
    peak HBM at one state copy; every XLA matmul path needs in+out = 16 GiB
    and cannot compile at this size).  Layer = Haar 1q gate per qubit + a CZ
    ladder (one fused elementwise parity pass, donated in-place)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from functools import partial
    from quest_tpu.ops.pallas_layer import apply_1q_layer_planes

    n = 30
    rs = np.random.RandomState(seed)
    gates = []
    for q in range(n):
        g = rs.randn(2, 2) + 1j * rs.randn(2, 2)
        u, r = np.linalg.qr(g)
        u = u * (np.diag(r) / np.abs(np.diag(r)))
        gates.append(np.stack([u.real, u.imag]).astype(np.float32))

    @partial(jax.jit, donate_argnums=(0, 1))
    def cz_ladder(re, im):
        k = jax.lax.iota(jnp.uint32, re.shape[0])
        par = jnp.zeros_like(k)
        for q in range(0, n - 1, 2):
            par = par ^ (((k >> q) & (k >> (q + 1))) & 1)
        sign = 1.0 - 2.0 * par.astype(re.dtype)
        return re * sign, im * sign

    @jax.jit
    def norm(re, im):
        return jnp.sum(re.astype(jnp.float64) ** 2
                       + im.astype(jnp.float64) ** 2)

    re = jnp.zeros(1 << n, dtype=jnp.float32).at[0].set(1.0)
    im = jnp.zeros(1 << n, dtype=jnp.float32)
    re, im = apply_1q_layer_planes(re, im, gates)  # compile + warm
    re, im = cz_ladder(re, im)
    float(re[0])
    ops = n + n // 2  # 30 dense 1q + 15 CZ pairs (range(0, n-1, 2) at n=30)
    # best of 2 passes (shared-chip noise windows observed up to 40x here)
    best = None
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(depth):
            re, im = apply_1q_layer_planes(re, im, gates)
            re, im = cz_ladder(re, im)
        total = float(norm(re, im))
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    assert abs(total - 1.0) < 1e-2, f"norm lost: {total}"
    value = (1 << n) * ops * depth / best
    cfg = {"qubits": n, "depth": depth, "precision": 1,
           "ops_per_layer": ops, "seconds": best,
           "engine": "pallas_inplace"}
    # 3 Pallas passes (layer17 + two fiber groups) + 1 fused CZ pass / layer
    cfg.update(_roofline(1 << n, 1, 4 * depth, best))
    return value, cfg


def bench_random_big(n=29, depth=6, seed=11):
    """Largest single-chip statevector (f32: a 29q state is 4 GiB — 30q's
    16 GiB in+out no longer fits 15.75 GiB HBM).  Covers the high-qubit
    regime of BASELINE config 3 as far as one chip allows; the 30-34q
    points need the multi-chip mesh (validated structurally by
    dryrun_multichip and the sharded QFT config).  Donating per-layer
    programs keep peak memory at in+out+temps; the ~13 ms/call dispatch
    latency is <5% of a ~350 ms layer."""
    import jax
    import jax.numpy as jnp
    from functools import partial
    from quest_tpu.circuit import _apply_one, random_circuit

    circuit = random_circuit(n, depth=1, seed=seed)
    circuit.optimize()
    ops = circuit.key()

    @partial(jax.jit, donate_argnums=(0,))
    def step(s):
        for op in ops:
            s = _apply_one(s, op)
        return s

    @jax.jit
    def norm(s):
        return jnp.sum(s[0].astype(jnp.float64) ** 2
                       + s[1].astype(jnp.float64) ** 2)

    state = jnp.zeros((2, 1 << n), dtype=jnp.float32).at[0, 0].set(1.0)
    state = step(state)  # compile + warm
    float(norm(state))
    t0 = time.perf_counter()
    for _ in range(depth):
        state = step(state)
    total = float(norm(state))
    dt = time.perf_counter() - t0
    assert abs(total - 1.0) < 1e-2, f"norm lost: {total}"
    value = (1 << n) * n * depth / dt
    cfg = {"qubits": n, "depth": depth, "precision": 1,
           "fused_ops": len(ops), "seconds": dt}
    cfg.update(_roofline(1 << n, 1, len(ops) * depth, dt))
    return value, cfg


def bench_clifford_t(n=20, depth=50, precision=2, seed=5):
    """Clifford+T layer: H/S/T per qubit + a CNOT ladder (BASELINE config 2)."""
    import numpy as np
    import jax.numpy as jnp
    from quest_tpu.circuit import Circuit, _apply_one

    rng = np.random.default_rng(seed)
    c = Circuit(n)
    for q in range(n):
        gate = rng.integers(0, 3)
        (c.h if gate == 0 else c.s if gate == 1 else c.t)(q)
    for q in range(0, n - 1, 2):
        c.cnot(q, q + 1)
    gates = len(c)
    c.optimize()
    ops = c.key()

    def layer(s):
        for op in ops:
            s = _apply_one(s, op)
        return s

    dtype = jnp.float32 if precision == 1 else jnp.float64
    state = jnp.zeros((2, 1 << n), dtype=dtype).at[0, 0].set(1.0)
    compute, total, dt, overhead = _run_layered(layer, state, depth)
    assert abs(total - 1.0) < 1e-2
    value = (1 << n) * gates * depth / compute
    cfg = {"qubits": n, "depth": depth, "precision": precision,
           "gates_per_layer": gates, "fused_ops": len(ops),
           "seconds": dt}
    cfg.update(_roofline(1 << n, precision, len(ops) * depth, compute))
    return value, cfg


def bench_pauli_expec(n=26, precision=1, reps=4):
    """Pauli-sum expectation of a (2n-1)-term TFIM Hamiltonian through the
    structured static-term kernels (ops/calc.py _structured_term) — the op
    class whose earlier traced-gather form ran ~1.5 s/term and crashed the
    remote worker's watchdog at 25 qubits.  Each term is one fused
    move+sign+reduce pass over the state."""
    import numpy as np
    import jax.numpy as jnp
    from quest_tpu.api import _pauli_sum_terms
    from quest_tpu.models import tfim_hamiltonian
    from quest_tpu.ops import calc as _calc

    dtype = jnp.float32 if precision == 1 else jnp.float64
    h = tfim_hamiltonian(n)
    terms = _pauli_sum_terms(np.asarray(h.pauli_codes))
    cf = jnp.asarray(np.asarray(h.term_coeffs))
    amp = 1.0 / float(np.sqrt(1 << n))
    state = jnp.full((2, 1 << n), 0.0, dtype=dtype).at[0].set(amp)  # |+..+>
    e = float(_calc.expec_pauli_sum_statevec(state, terms, cf))  # compile+warm
    assert abs(e - (-n)) < 1e-2, e  # <+|TFIM|+> = -field*n
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(reps):
            e = float(_calc.expec_pauli_sum_statevec(state, terms, cf))
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    value = len(terms) * (1 << n) * reps / best
    cfg = {"qubits": n, "precision": precision, "terms": len(terms),
           "reps": reps, "seconds": best}
    cfg.update(_roofline(1 << n, precision, len(terms) * reps, best))
    return value, cfg


def bench_vmap_batch(n=16, batch=32, depth=20, seed=11):
    """An ensemble of independent circuits simulated at once via jax.vmap —
    a capability the reference has no analogue for (one process = one
    register).  Small states cannot saturate the chip alone (a single 16q
    circuit measures ~4x baseline); batching fills the MXU/HBM pipeline
    (measured ~29x gain at batch 32)."""
    import jax
    import jax.numpy as jnp
    from quest_tpu.circuit import _apply_one, random_circuit

    c = random_circuit(n, depth=1, seed=seed)
    c.optimize()
    ops = c.key()

    def layer(s):
        for op in ops:
            s = _apply_one(s, op)
        return s

    @jax.jit
    def run(ss, iters):
        def body(_, st):
            return jax.vmap(layer)(st)
        ss = jax.lax.fori_loop(0, iters, body, ss)
        return jnp.sum(ss[:, 0] ** 2 + ss[:, 1] ** 2)

    states = jnp.zeros((batch, 2, 1 << n), dtype=jnp.float32).at[:, 0, 0].set(1.0)
    float(run(states, 1))  # compile + warm
    best = None
    total = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        total = float(run(states, depth))
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    assert abs(total - batch) < 1e-2 * batch, total
    value = batch * (1 << n) * n * depth / best
    cfg = {"qubits": n, "batch": batch, "depth": depth, "precision": 1,
           "ops_per_layer": len(ops), "seconds": best}
    cfg.update(_roofline(batch << n, 1, len(ops) * depth, best))
    return value, cfg


def bench_trajectories(n=20, trajectories=256, batch=16, seed=3):
    """Monte-Carlo wavefunction ensemble of a NOISY circuit (one Haar-ish ry
    layer + CNOT ladder + per-qubit depolarising + damping) — noise at
    statevector cost (quest_tpu/trajectories.py).  The exact density
    representation of this 20-qubit system is a 40-qubit Choi vector (8 TB):
    this workload exists on one chip ONLY through the unraveling."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import quest_tpu as qt
    from quest_tpu.models import tfim_hamiltonian

    pc = qt.ParamCircuit(n)
    t = pc.params(n)
    for q in range(n):
        pc.ry(q, t[q])
    for q in range(0, n - 1, 2):
        pc.cnot(q, q + 1)
    for q in range(n):
        pc.depolarise(q, 0.02)
    pc.damp(0, 0.05)
    gates = n + n // 2 + n + 1  # rotations + ladder + channels
    h = tfim_hamiltonian(n)
    params = jnp.asarray(np.random.default_rng(seed).normal(0.3, 0.2, n),
                         dtype=jnp.float32)
    fn = qt.trajectory_expectation_fn(pc, h, trajectories, batch=batch)
    key = jax.random.PRNGKey(0)
    e = float(fn(key, params))  # compile + warm
    assert np.isfinite(e) and abs(e) < 2 * n, e
    best = None
    for _ in range(2):
        t0 = time.perf_counter()
        e = float(fn(key, params))
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    value = trajectories * (1 << n) * gates / best
    cfg = {"qubits": n, "trajectories": trajectories, "batch": batch,
           "gates_per_trajectory": gates, "precision": 1, "seconds": best,
           "expectation": e}
    cfg.update(_roofline(trajectories << n, 1, gates, best))
    return value, cfg


def bench_density(n=14, depth=5, precision=2, seed=7):
    """Density-matrix layer on the Choi-flattened 2n-qubit vector: Haar 1q
    gate + shadow, then mixDamping and mixDepolarising per qubit pair
    (BASELINE config 4).

    f32 (PR 15) records the layer as a ``DensityCircuit`` and compiles it
    through ``compile_circuit(engine="auto")``: on a TPU the epoch
    executor fuses the 42-op mirrored layer + channels into ~3 aliased
    superoperator passes (the row carries the plan breakdown and the
    model-vs-measured ledger record); on CPU auto resolves to one fused
    XLA program.  f64 runs ONE barriered donating program per layer (the
    barriers stop XLA from overlapping two ops' state-sized temporaries,
    which is what pushed an unbarriered 42-op f64 program over HBM; r04's
    per-op-program fallback was dispatch-bound at ~0.24 s per tunnel
    round-trip)."""
    import numpy as np
    import jax.numpy as jnp
    from quest_tpu.ops import apply as _ap
    from quest_tpu.ops import decoherence as _deco

    rng = np.random.default_rng(seed)
    dtype = jnp.float32 if precision == 1 else jnp.float64

    gates = []
    for q in range(n):
        g = rng.normal(size=(2, 2)) + 1j * rng.normal(size=(2, 2))
        u, r = np.linalg.qr(g)
        u = u * (np.diag(r) / np.abs(np.diag(r)))
        gates.append((q, _ap.mat_pair(u), _ap.mat_pair(u.conj())))

    import jax

    # rho = |0><0| flattened; donation consumes the buffer, so each timed
    # call gets a fresh state
    def fresh():
        return jnp.zeros((2, 1 << (2 * n)), dtype=dtype).at[0, 0].set(1.0)

    from functools import partial

    from quest_tpu import _compat

    # trace of rho = sum of real diagonal, via strided slice (elements
    # k*(2^n+1)) — no (2^n, 2^n) square view materialised
    @jax.jit
    def trace_of(s):
        dim = 1 << n
        diag = jax.lax.slice(s[0], (0,), (dim * dim,), (dim + 1,))
        return jnp.sum(diag.astype(jnp.float64))

    num_ops = 2 * n + n  # gate+shadow per qubit, channel per qubit

    if precision == 1:
        # PR 15: the f32 layer is ONE compiled noisy-circuit program
        # through compile_circuit(engine="auto") on the Choi-doubled
        # register (circuit.DensityCircuit): the mirrored Haar layer AND
        # the damping/depolarising channels lower together — on a TPU the
        # epoch executor fuses the 42-op layer into ~3 aliased passes with
        # the channels as superoperator stages; on CPU auto resolves to
        # the XLA engine and the row documents the spec decision + plan
        from quest_tpu.circuit import DensityCircuit, compile_circuit
        from quest_tpu.parallel import planner as _planner

        dc = DensityCircuit(n)
        for q, up, _ in gates:
            dc.unitary(q, up[0] + 1j * up[1])
        for q in range(0, n, 2):
            dc.damp(q, 0.02)
        for q in range(1, n, 2):
            dc.depolarise(q, 0.02)

        spec = _planner.select_engine(dc, 1, backend="tpu")
        run_layer = compile_circuit(dc)         # engine="auto" default

        @partial(jax.jit, donate_argnums=(0,))
        def run(s, iters):
            def body(_, st):
                return run_layer(st)
            return trace_of(jax.lax.fori_loop(0, iters, body, s))

        # x64 off for any Mosaic lowering (same constraint as
        # pallas_layer.apply_1q_layer); f32 operands are unaffected
        with _compat.enable_x64(False):
            t0 = time.perf_counter()
            float(run(fresh(), 1))
            compile_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            base = float(run(fresh(), 0))
            overhead = time.perf_counter() - t0
            t0 = time.perf_counter()
            trace = float(run(fresh(), depth))
            dt = time.perf_counter() - t0
        compute = max(dt - overhead, 1e-9)
    else:
        # ONE donating program per LAYER (42 ops), each op bounded by an
        # optimization_barrier so XLA's scheduler cannot overlap two ops'
        # state-sized temporaries (unbarriered, a 42-op f64 program exceeds
        # HBM from inter-op liveness; r04 worked around it with one program
        # per OP, which made the row dispatch-bound at ~0.24 s per tunnel
        # round-trip x 126 ops).  Gates route through the engine's chunked
        # fast-1q f64 kernel (_dense_1q_f64); the trace assert below guards
        # the X64-rewriter miscompile classes documented in docs/DESIGN.md
        # (plane-pair/multi-op variants of this layer compute wrong norms
        # on-chip while passing on CPU).
        @partial(jax.jit, donate_argnums=(0,))
        def layer_f64(s):
            for q, up, upc in gates:
                # two fast single-target passes beat the fused 2-target
                # superoperator gather HERE (measured 20.5 s vs 23.8 s for
                # the 3-layer run): inside one compiled program there is no
                # dispatch to save, and the fused form's coefficient-gather
                # accumulator costs more than the second pass.  The fused
                # dispatch in apply_matrix_density still wins EAGERLY,
                # where each program costs a ~0.24 s tunnel round-trip.
                s = _ap.apply_matrix(s, jnp.asarray(up, dtype=s.dtype), (q,))
                s = jax.lax.optimization_barrier(s)
                s = _ap.apply_matrix(s, jnp.asarray(upc, dtype=s.dtype),
                                     (q + n,))
                s = jax.lax.optimization_barrier(s)
            for q in range(0, n, 2):
                s = _deco.mix_damping(s, jnp.asarray(0.02, jnp.float64), q, n)
                s = jax.lax.optimization_barrier(s)
            for q in range(1, n, 2):
                s = _deco.mix_depolarising(s, jnp.asarray(0.02, jnp.float64),
                                           q, n)
                s = jax.lax.optimization_barrier(s)
            return s

        s = layer_f64(fresh())  # compile + warm
        float(trace_of(s))
        del s
        # best of 2 timed passes against tunnel-noise windows
        dt = None
        for _ in range(2):
            s = fresh()
            t0 = time.perf_counter()
            for _ in range(depth):
                s = layer_f64(s)
            trace = float(trace_of(s))
            run_dt = time.perf_counter() - t0
            dt = run_dt if dt is None else min(dt, run_dt)
            del s
        compute = max(dt, 1e-9)

    assert abs(trace - 1.0) < 1e-2, f"trace not preserved: {trace}"
    value = (1 << (2 * n)) * num_ops * depth / compute
    cfg = {"qubits": n, "depth": depth, "precision": precision,
           "ops_per_layer": num_ops, "seconds": dt}
    if precision == 1:
        import jax
        from quest_tpu.obs import global_ledger, hbm_watermark
        model = spec["model"] or {}
        live_model = (model.get("pallas_seconds")
                      if run_layer.engine == "pallas"
                      else model.get("xla_seconds"))
        wm = hbm_watermark()
        drift = global_ledger().record(
            f"densmatr_{n}q_layer", engine=run_layer.engine, num_devices=1,
            platform=jax.devices()[0].platform,
            predicted_seconds=(live_model * depth if live_model else None),
            measured_seconds=compute,
            predicted_hbm_passes=(model.get("pallas_hbm_passes")
                                  if run_layer.engine == "pallas"
                                  else model.get("xla_hbm_passes")),
            predicted_collectives=0, measured_hlo_collectives=0,
            compile_seconds=compile_s,
            hbm_peak_bytes=(wm or {}).get("peak_bytes_in_use"))
        cfg.update({
            "density_qubits": n, "register_qubits": 2 * n,
            "model_vs_measured": drift.as_dict(),
            "engine_live": run_layer.engine,
            "engine_live_reason": run_layer.engine_reason,
            "engine_tpu_spec": spec["engine"],
            "engine_tpu_spec_reason": spec["reason"],
            "fused_passes_per_layer": model.get("pallas_hbm_passes"),
            "superop_pass_breakdown": model.get("pallas_pass_breakdown"),
            "model_engine_speedup": (
                model["xla_seconds"] / model["pallas_seconds"]
                if model.get("pallas_seconds") else None)})
        _stamp_counters(cfg, compile_s)
    cfg.update(_roofline(1 << (2 * n), precision, num_ops * depth, compute))
    return value, cfg


def bench_density_kraus_auto(n_ceiling=16, n_measured=12, layers=2, iters=2,
                             seed=19):
    """``densmatr_16q_kraus_auto_engine``: the density-window CEILING row.

    A 16-qubit density register is a 32-qubit Choi-doubled vector — one
    past the epoch executor's int32-index ceiling (so ``engine="auto"``
    resolves to XLA with the density-window reason) and, at 4^16 amps,
    past any single chip's HBM regardless of engine.  The row RECORDS that
    decision (the boundary documentation, the density twin of
    vqe_16q_auto_engine's n >= 17 floor note) and MEASURES the largest
    in-window Kraus workload instead: ``n_measured``-density-qubit mixed
    unitary + per-qubit general Kraus channel layers under auto vs
    forced-XLA, with the fused superoperator plan and the
    model-vs-measured ledger record."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import partial
    from quest_tpu import _compat
    from quest_tpu.circuit import DensityCircuit, compile_circuit
    from quest_tpu.obs import global_ledger, hbm_watermark
    from quest_tpu.parallel import planner as _planner

    rng = np.random.default_rng(seed)

    def haar():
        g = rng.normal(size=(2, 2)) + 1j * rng.normal(size=(2, 2))
        u, r = np.linalg.qr(g)
        return u * (np.diag(r) / np.abs(np.diag(r)))

    from quest_tpu.ops.decoherence import channel_kraus

    def noisy(n, depth):
        dc = DensityCircuit(n)
        for layer in range(depth):
            for q in range(n):
                dc.unitary(q, haar())
            for q in range(layer % 2, n, 2):
                # the canonical damping Kraus pair (decoherence.py — the
                # same definition the equivalence prover certifies)
                dc.kraus((q,), channel_kraus("damp", 0.02 + 0.005 * layer))
        return dc

    # the ceiling decision: 16 density qubits = 32 register qubits
    ceiling = noisy(n_ceiling, 1)
    spec16 = _planner.select_engine(ceiling, 1, backend="tpu")
    assert spec16["engine"] == "xla", spec16
    assert "density" in spec16["reason"], spec16["reason"]

    # the measured in-window workload
    dc = noisy(n_measured, layers)
    spec = _planner.select_engine(dc, 1, backend="tpu")
    run_auto = compile_circuit(dc)
    run_xla = compile_circuit(dc, engine="xla")

    dim = 1 << n_measured

    @jax.jit
    def trace_of(s):
        diag = jax.lax.slice(s[0], (0,), (dim * dim,), (dim + 1,))
        return jnp.sum(diag.astype(jnp.float64))

    def fresh():
        return jnp.zeros((2, 1 << (2 * n_measured)),
                         jnp.float32).at[0, 0].set(1.0)

    def timed(run):
        @partial(jax.jit, donate_argnums=(0,))
        def body(s, k):
            def one(_, st):
                return run(st)
            return trace_of(jax.lax.fori_loop(0, k, one, s))

        with _compat.enable_x64(False):
            t0 = time.perf_counter()
            float(body(fresh(), 1))
            compile_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            float(body(fresh(), 0))
            overhead = time.perf_counter() - t0
            t0 = time.perf_counter()
            trace = float(body(fresh(), iters))
            dt = time.perf_counter() - t0
        assert abs(trace - 1.0) < 1e-2, f"trace not preserved: {trace}"
        return max(dt - overhead, 1e-9), dt, compile_s

    compute_a, dt, compile_s = timed(run_auto)
    compute_x, _, _ = timed(run_xla)

    gates = len(dc.ops)
    value = (1 << (2 * n_measured)) * gates * iters / compute_a
    model = spec["model"] or {}
    live_model = (model.get("pallas_seconds")
                  if run_auto.engine == "pallas"
                  else model.get("xla_seconds"))
    wm = hbm_watermark()
    drift = global_ledger().record(
        f"densmatr_kraus_{n_measured}q", engine=run_auto.engine,
        num_devices=1, platform=jax.devices()[0].platform,
        predicted_seconds=(live_model * iters if live_model else None),
        measured_seconds=compute_a,
        predicted_hbm_passes=(model.get("pallas_hbm_passes")
                              if run_auto.engine == "pallas"
                              else model.get("xla_hbm_passes")),
        predicted_collectives=0, measured_hlo_collectives=0,
        compile_seconds=compile_s,
        hbm_peak_bytes=(wm or {}).get("peak_bytes_in_use"))
    cfg = {"qubits": n_measured, "density_qubits_measured": n_measured,
           "density_qubits_ceiling": n_ceiling,
           "register_qubits": 2 * n_measured,
           "layers": layers, "iters": iters, "precision": 1,
           "ops": gates, "seconds": dt,
           "ceiling_decision": {"engine": spec16["engine"],
                                "reason": spec16["reason"]},
           "model_vs_measured": drift.as_dict(),
           "engine_live": run_auto.engine,
           "engine_live_reason": run_auto.engine_reason,
           "engine_tpu_spec": spec["engine"],
           "engine_tpu_spec_reason": spec["reason"],
           "fused_passes": model.get("pallas_hbm_passes"),
           "superop_pass_breakdown": model.get("pallas_pass_breakdown"),
           "model_engine_speedup": (
               model["xla_seconds"] / model["pallas_seconds"]
               if model.get("pallas_seconds") else None),
           "amps_per_sec_xla_engine":
               (1 << (2 * n_measured)) * gates * iters / compute_x,
           "vs_xla_engine": compute_x / compute_a}
    passes = (model.get("pallas_hbm_passes") or gates) \
        if run_auto.engine == "pallas" else gates
    cfg.update(_roofline(1 << (2 * n_measured), 1, passes * iters,
                         compute_a))
    _stamp_counters(cfg, compile_s)
    return value, cfg


def bench_qft_inplace(n, bit_reversal):
    """QFT through the in-place engine (ops/qft_inplace.py).  At n=30 —
    the single-chip ceiling, where the swap network's second state copy
    cannot fit — output is unordered (bit-reversed, the standard FFT
    convention) and the gate count credits H + the n(n-1)/2 controlled
    phases the fused ladders implement, NOT the unapplied swaps; at
    n <= 29 the ordered transform includes the reversal and counts the
    n/2 swaps it implements."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from quest_tpu.ops.qft_inplace import qft_planes

    re = jnp.full((1 << n,), np.float32(1.0 / np.sqrt(1 << n)), jnp.float32)
    im = jnp.zeros((1 << n,), jnp.float32)
    re, im = qft_planes(re, im, bit_reversal=bit_reversal)  # compile + warm
    a0 = float(re[0])
    assert abs(a0 - 1.0) < 1e-3, f"QFT(|+..+>) != |0..0>: amp0={a0}"
    best = None
    for _ in range(2):  # best-of-2 against tunnel noise windows
        t0 = time.perf_counter()
        re, im = qft_planes(re, im, bit_reversal=bit_reversal)
        float(re[0])
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    gates = n + n * (n - 1) // 2 + (n // 2 if bit_reversal else 0)
    value = (1 << n) * gates / best
    cfg = {"qubits": n, "precision": 1, "gates": gates, "seconds": best,
           "engine": "pallas_inplace", "bit_reversed_output": not bit_reversal}
    # per high-q stage (q=n-1..17): two half-state _h_flip passes (= 1
    # state pass) + one in-place Pallas ladder pass; ONE fused tail pass
    # covers all 33 remaining circuit passes (q<=16); the ordered mode
    # adds 3 permutation passes per plane (= 3 state passes)
    cfg.update(_roofline(1 << n, 1,
                         2 * (n - 17) + 1 + (3 if bit_reversal else 0), best))
    return value, cfg


def bench_qft30_api(n=30):
    """The 30-qubit QFT through the PUBLIC API front door: a plane-storage
    Qureg (qureg.py PLANE_STORAGE_MIN_BYTES) whose buffers the in-place
    engine consumes directly; applyFullQFT defers the trailing bit-reversal
    into the register's qubit_map, and the correctness probe reads the
    logical amplitude THROUGH the map (getAmp translates indices)."""
    import quest_tpu as qt

    env = qt.createQuESTEnv(num_devices=1)
    q = qt.createQureg(n, env, dtype="float32")
    assert q.uses_plane_storage(), "expected plane-pair storage at 30q f32"
    qt.initPlusState(q)
    qt.applyFullQFT(q)  # compile + warm
    assert q.qubit_map is not None  # deferred bit-reversal recorded
    a0 = qt.getAmp(q, 0)
    assert abs(a0.real - 1.0) < 1e-3, f"QFT(|+..+>) != |0..0>: amp0={a0}"
    best = None
    for _ in range(2):
        qt.initPlusState(q)
        t0 = time.perf_counter()
        qt.applyFullQFT(q)
        a0 = qt.getAmp(q, 0)  # device->host scalar bounds the timing
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    assert abs(a0.real - 1.0) < 1e-3, a0
    gates = n + n * (n - 1) // 2
    value = (1 << n) * gates / best
    cfg = {"qubits": n, "precision": 1, "gates": gates, "seconds": best,
           "engine": "pallas_inplace", "via": "public API (plane Qureg)",
           "bit_reversed_output": True}
    cfg.update(_roofline(1 << n, 1, 2 * (n - 17) + 1, best))
    return value, cfg


_HLO_COLLECTIVES = ("all-to-all", "collective-permute", "all-gather",
                    "all-reduce", "reduce-scatter")


def _hlo_collective_count(compiled_text: str) -> int:
    """Collective instruction DEFINITIONS in compiled HLO text — the
    measured comm-pass count of a program (the static comm_plan predicts;
    this observes what the partitioner actually emitted).  Async spellings
    (``op-start``) count like sync ones: on TPU the overlapped executor's
    collectives lower as start/done pairs and must not vanish from the
    column (``-done`` is the same collective completing, not a second
    one)."""
    import re
    pat = re.compile(r"= \S+ (" + "|".join(_HLO_COLLECTIVES)
                     + r")(-start)?\(")
    return len(pat.findall(compiled_text))


def bench_serve_vqe16_batch64(requests=64, n=16, layers=1):
    """64 structurally-identical, differently-parameterized 16q VQE ansatz
    circuits through QuESTService vs the per-circuit compile-and-run loop
    — the serving subsystem's headline row (docs/SERVING.md).

    The per-circuit loop pays one XLA compile PER TENANT (a program keyed
    on angle values is a fresh program for every angle assignment); the
    service canonicalizes all 64 to one structural class, compiles ONE
    parameter-lifted (state, params) program, and runs one 64-wide
    microbatch.  Value = serve-path amp updates/s; the config records the
    compile counts (must be 1 vs 64), both wall times and the speedup, and
    the mean batch size from the service metrics."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from quest_tpu.circuit import _run_ops_routed
    from quest_tpu.serve import CompileCache, QuESTService
    from quest_tpu.serve.selftest import vqe_ansatz

    platform = jax.devices()[0].platform
    dtype = jnp.float64 if platform == "cpu" else jnp.float32
    circuits = [vqe_ansatz(n, layers, seed=s) for s in range(requests)]
    gates = len(circuits[0].ops)

    def fresh():
        return jnp.zeros((2, 1 << n), dtype).at[0, 0].set(1.0)

    # per-circuit loop: a fresh jit closure per tenant = one compile each —
    # exactly what a pre-serve caller pays for an angle sweep
    t0 = time.perf_counter()
    eager_out = None
    for c in circuits:
        run = jax.jit(lambda s, _ops=c.key(): _run_ops_routed(s, _ops))
        eager_out = run(fresh())
    jax.block_until_ready(eager_out)
    eager_seconds = time.perf_counter() - t0

    cache = CompileCache()
    svc = QuESTService(max_batch=requests, max_delay_ms=50.0,
                      max_queue=requests, dtype=dtype, cache=cache,
                      start=False)
    t0 = time.perf_counter()
    futs = [svc.submit(c) for c in circuits]
    svc.start()
    if not svc.drain(timeout=1200):
        raise RuntimeError("serve drain timed out")
    results = [f.result(timeout=120) for f in futs]
    serve_seconds = time.perf_counter() - t0
    svc.shutdown()

    # correctness guard: last request vs its per-circuit program
    worst = float(np.abs(results[-1].state - np.asarray(eager_out)).max())
    tol = 1e-12 if dtype == jnp.float64 else 1e-5
    assert worst < tol, f"serve result drifted {worst} from per-circuit run"
    snap = cache.snapshot()
    assert snap["compiles"] == 1, f"expected ONE compile, got {snap}"
    hist = svc.metrics_dict()["histograms"]["batch_size"]
    value = (1 << n) * gates * requests / max(serve_seconds, 1e-9)
    cfg = {"qubits": n, "requests": requests, "gates_per_circuit": gates,
           "precision": 2 if dtype == jnp.float64 else 1,
           "platform": platform,
           "serve_seconds": serve_seconds,
           "eager_loop_seconds": eager_seconds,
           "speedup_vs_per_circuit_loop": eager_seconds
           / max(serve_seconds, 1e-9),
           "serve_compiles": int(snap["compiles"]),
           "eager_compiles": requests,
           "cache_hit_rate": snap["hit_rate"],
           "mean_batch_size": hist["mean"],
           "max_abs_diff_vs_per_circuit": worst,
           "seconds": serve_seconds}
    return value, cfg


def bench_vqe_grad_16q_batch64(requests=64, n=16, layers=1):
    """64 same-ansatz, different-angle 16q GRADIENT requests through
    ``QuESTService.submit_gradient`` (quest_tpu/grad) — the gradient-
    serving headline row (docs/SERVING.md "Gradient serving").

    One structural class => ONE compile for the whole sweep (asserted),
    one 64-wide ``lax.map`` adjoint microbatch.  Two baselines, both
    measured on a subset and reported per-request (each is minutes-per-
    request territory at 64 tenants):

    - **central finite differences** through the jitted energy program
      (compiled once): 2·P circuit executions per gradient — what a
      QuEST-reference user hand-rolls, on our fastest forward path;
    - **jax.grad through the unlifted program**: taped reverse-mode with
      a FRESH trace per tenant (the pre-serve angle-sweep cost: a program
      keyed on the closure is a fresh compile per angle assignment).

    Value = gradients/second through the serve path; the config records
    per-request walls and the speedups.  Asserts the served gradients
    match finite differences (tolerance-banded) and that the serve path
    is STRICTLY faster per request than both baselines."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from quest_tpu.autodiff import expectation_fn
    from quest_tpu.models import hardware_efficient_ansatz, tfim_hamiltonian
    from quest_tpu.serve import CompileCache, QuESTService

    platform = jax.devices()[0].platform
    pc = hardware_efficient_ansatz(n, layers)
    hamil = tfim_hamiltonian(n)
    num_params = pc.num_params
    gates = len(pc.ops)
    rng = np.random.default_rng(17)
    params = [rng.uniform(-np.pi, np.pi, num_params)
              for _ in range(requests)]

    cache = CompileCache()
    svc = QuESTService(max_batch=requests, max_delay_ms=50.0,
                      max_queue=requests, cache=cache, start=False)
    t0 = time.perf_counter()
    futs = [svc.submit_gradient(pc, p, hamil) for p in params]
    svc.start()
    if not svc.drain(timeout=2400):
        raise RuntimeError("gradient serve drain timed out")
    results = [f.result(timeout=300) for f in futs]
    serve_seconds = time.perf_counter() - t0
    svc.shutdown()
    snap = cache.snapshot()
    assert snap["compiles"] == 1, f"expected ONE compile, got {snap}"
    serve_per_req = serve_seconds / requests

    # baseline (a): central finite differences through ONE jitted energy
    # program — 2P executions per gradient, measured on one request
    efn = expectation_fn(pc, hamil)
    jax.block_until_ready(efn(jnp.asarray(params[0])))  # compile outside
    p0 = np.asarray(params[0], np.float64)
    eps = 1e-5
    t0 = time.perf_counter()
    fd = np.zeros(num_params)
    for i in range(num_params):
        up, dn = p0.copy(), p0.copy()
        up[i] += eps
        dn[i] -= eps
        fd[i] = (float(efn(jnp.asarray(up))) - float(efn(jnp.asarray(dn)))) \
            / (2 * eps)
    fd_per_req = time.perf_counter() - t0
    worst = float(np.abs(results[0].gradient - fd).max())
    assert worst < 1e-5, f"served gradient drifted {worst} from central FD"

    # baseline (b): unlifted jax.grad, fresh trace per tenant (compile
    # cost included — that IS the unlifted cost model), 2 tenants measured
    unlifted_n = 2
    t0 = time.perf_counter()
    for p in params[:unlifted_n]:
        vg = jax.jit(jax.value_and_grad(expectation_fn(pc, hamil)))
        v, g = vg(jnp.asarray(p))
        jax.block_until_ready(g)
    unlifted_per_req = (time.perf_counter() - t0) / unlifted_n

    assert serve_per_req < fd_per_req, (serve_per_req, fd_per_req)
    assert serve_per_req < unlifted_per_req, (serve_per_req,
                                              unlifted_per_req)
    hist = svc.metrics_dict()["histograms"]["batch_size"]
    value = requests / max(serve_seconds, 1e-9)
    cfg = {"qubits": n, "requests": requests, "gates_per_circuit": gates,
           "num_params": num_params,
           "hamil_terms": hamil.num_sum_terms,
           "precision": 2, "platform": platform,
           "serve_seconds": serve_seconds,
           "serve_seconds_per_request": serve_per_req,
           "fd_seconds_per_request": fd_per_req,
           "fd_evals_per_request": 2 * num_params,
           "unlifted_jaxgrad_seconds_per_request": unlifted_per_req,
           "unlifted_requests_measured": unlifted_n,
           "speedup_vs_fd": fd_per_req / max(serve_per_req, 1e-9),
           "speedup_vs_unlifted_jaxgrad": unlifted_per_req
           / max(serve_per_req, 1e-9),
           "serve_compiles": int(snap["compiles"]),
           "cache_hit_rate": snap["hit_rate"],
           "mean_batch_size": hist["mean"],
           "max_abs_diff_vs_fd": worst,
           "seconds": serve_seconds}
    return value, cfg


def bench_serve_vqe16_probed_overhead(requests=64, n=16, layers=1):
    """The numeric-health overhead row (docs/OBSERVABILITY.md "Numeric
    health"): the serve_vqe_16q_batch64 workload served twice — plain, and
    through the probe-instrumented program variants (obs/numerics.py) —
    on fresh caches.  Value = probed/unprobed wall ratio; the contract is
    probe overhead <= 5% (asserted: probes are pure reductions beside the
    main dataflow, a handful of extra FLOPs against a 2^16-amp gate
    chain).  Each side runs twice and takes the min wall so a scheduler
    blip cannot fake (or mask) an overhead regression.  Also asserts the
    probed side's results carry clean numeric_health records and the
    ledger saw zero findings — the overhead row doubles as a clean-
    workload numeric gate."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from quest_tpu.obs import numerics as qnum
    from quest_tpu.serve import CompileCache, QuESTService
    from quest_tpu.serve.selftest import vqe_ansatz

    platform = jax.devices()[0].platform
    dtype = jnp.float64 if platform == "cpu" else jnp.float32
    circuits = [vqe_ansatz(n, layers, seed=s) for s in range(requests)]
    gates = len(circuits[0].ops)

    def serve_round(probes):
        ledger = qnum.NumericLedger()
        svc = QuESTService(max_batch=requests, max_delay_ms=50.0,
                          max_queue=requests, dtype=dtype,
                          cache=CompileCache(), probes=probes,
                          numeric_ledger=ledger, start=False)
        walls = []
        results = None
        for _ in range(2):
            t0 = time.perf_counter()
            futs = [svc.submit(c) for c in circuits]
            svc.start()
            if not svc.drain(timeout=1200):
                raise RuntimeError("probed-overhead drain timed out")
            results = [f.result(timeout=120) for f in futs]
            walls.append(time.perf_counter() - t0)
        svc.shutdown()
        return min(walls), results, ledger

    plain_s, plain_res, _ = serve_round(False)
    probed_s, probed_res, ledger = serve_round(True)
    # the probed side must change NOTHING but the telemetry
    assert all(r.numeric_health is None for r in plain_res)
    assert all(r.numeric_health is not None
               and not r.numeric_health["findings"] for r in probed_res), \
        "probed serve flagged a clean workload"
    snap = ledger.snapshot()
    assert snap["nan_total"] == 0 and snap["drift_total"] == 0, snap
    # byte-equality over EVERY request pair (circuits are seeded
    # per-index): a divergence in any batch position must fail the row
    worst = max(float(np.abs(p.state - np.asarray(u.state)).max())
                for p, u in zip(probed_res, plain_res))
    assert worst == 0.0, f"probed result drifted {worst} from unprobed"
    value = probed_s / max(plain_s, 1e-9)
    assert value <= 1.05, (
        f"probe overhead {100 * (value - 1):.1f}% exceeds the 5% budget "
        f"(probed {probed_s:.3f}s vs {plain_s:.3f}s)")
    cfg = {"qubits": n, "requests": requests, "gates_per_circuit": gates,
           "precision": 2 if dtype == jnp.float64 else 1,
           "platform": platform,
           "probed_seconds": probed_s,
           "unprobed_seconds": plain_s,
           "overhead_frac": value - 1.0,
           "probed_requests": int(snap["probed_total"]),
           "numeric_findings": snap["nan_total"] + snap["drift_total"],
           "seconds": probed_s}
    return value, cfg


def bench_serve_deploy_rps(requests_per_class=16, n=12, replicas=2):
    """Aggregate requests/sec of a 2-replica deployment (quest_tpu/deploy:
    affinity router + per-replica services) vs ONE QuESTService on the
    SAME workload — the scale-out row of docs/DEPLOY.md.

    Three structural classes (VQE ansatz depths 1-3) x
    ``requests_per_class`` tenants each; the router's rendezvous affinity
    spreads classes across replica caches, and replica workers overlap
    (JAX releases the GIL during device execution).  Value = deployment
    requests/s; the config records both sides, the speedup, the per-replica
    routed counts and the bit-identity spot check."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from quest_tpu.deploy import ReplicaPool
    from quest_tpu.serve import CompileCache, QuESTService
    from quest_tpu.serve.selftest import vqe_ansatz

    platform = jax.devices()[0].platform
    dtype = jnp.float64 if platform == "cpu" else jnp.float32
    classes = [[vqe_ansatz(n, layers, seed=100 * layers + s)
                for s in range(requests_per_class)]
               for layers in (1, 2, 3)]
    total = sum(len(cs) for cs in classes)

    def storm(submit, start, drain):
        futs = []
        longest = max(len(cs) for cs in classes)
        t0 = time.perf_counter()
        for i in range(longest):
            for cs in classes:
                if i < len(cs):
                    futs.append((cs[i], submit(cs[i])))
        start()
        if not drain(600):
            raise RuntimeError("deploy bench drain timed out")
        dt = time.perf_counter() - t0
        return futs, dt

    svc = QuESTService(max_batch=16, max_delay_ms=5.0, dtype=dtype,
                      cache=CompileCache(), start=False)
    _futs, single_seconds = storm(svc.submit, svc.start,
                                  lambda t: svc.drain(timeout=t))
    svc.shutdown()

    pool = ReplicaPool(replicas, max_batch=16, max_delay_ms=5.0,
                       dtype=dtype, start=False)
    futs, pool_seconds = storm(pool.submit, pool.start,
                               lambda t: pool.drain(timeout=t))
    # bit-identity spot check: one result per class vs a serial oracle
    oracle = CompileCache()
    for cs in classes:
        circ = cs[0]
        res = next(f for c, f in futs if c is circ).result(timeout=60)
        st = jnp.zeros((2, 1 << n), dtype).at[0, 0].set(1.0)
        want = np.asarray(oracle.execute(circ.key(), st, num_qubits=n))
        assert np.array_equal(res.state, want), "deployment drifted"
    routed = {str(r.index):
              int(pool.metrics.counter("routed_total",
                                       labels={"replica": str(r.index)}))
              for r in pool.replicas}
    pool.shutdown()
    value = total / max(pool_seconds, 1e-9)
    cfg = {"qubits": n, "replicas": replicas, "requests": total,
           "classes": len(classes), "platform": platform,
           "precision": 2 if dtype == jnp.float64 else 1,
           "pool_seconds": pool_seconds,
           "single_replica_seconds": single_seconds,
           "single_replica_rps": total / max(single_seconds, 1e-9),
           "deploy_rps": value,
           "speedup_vs_single": single_seconds / max(pool_seconds, 1e-9),
           "routed_per_replica": routed,
           "seconds": pool_seconds}
    _stamp_counters(cfg)
    return value, cfg


def bench_serve_coldstart(n_classes=3):
    """Warm-loaded vs cold-compiled replica cold start (deploy/persist.py:
    the persistent executable store) on the serve selftest's class mix.
    Value = cold/warm speedup; the config carries both cold-start walls and
    the compile evidence (warm side must report ZERO compiles — asserted,
    not just recorded)."""
    import shutil
    import tempfile
    import jax
    import jax.numpy as jnp
    from quest_tpu.deploy.selftest import coldstart_compare
    from quest_tpu.serve.selftest import workload_classes

    platform = jax.devices()[0].platform
    # f64 probe states fail to compile on the TPU backend (same split as
    # bench_serve_deploy_rps)
    dtype = jnp.float64 if platform == "cpu" else jnp.float32
    reps = [(label, cs[0])
            for label, cs, _ in workload_classes(1)][:n_classes]
    store_dir = tempfile.mkdtemp(prefix="quest_bench_store_")
    try:
        rep = coldstart_compare(store_dir, reps, dtype=dtype)
    finally:
        shutil.rmtree(store_dir, ignore_errors=True)
    assert rep["warm"]["compiles"] == 0, rep["warm"]
    assert rep["warm"]["coldstart_seconds"] < rep["cold"]["coldstart_seconds"], rep
    value = rep["speedup"]
    cfg = {"classes": [label for label, _ in reps], "platform": platform,
           "warm_coldstart_seconds": rep["warm"]["coldstart_seconds"],
           "cold_coldstart_seconds": rep["cold"]["coldstart_seconds"],
           "warm_compiles": rep["warm"]["compiles"],
           "cold_compiles": rep["cold"]["compiles"],
           "warm_persist_hits": rep["warm"]["persist_hits"],
           "seconds": rep["warm"]["coldstart_seconds"]}
    _stamp_counters(cfg)
    return value, cfg


_SCHED_PAIR_CHUNKS = 4  # pipeline depth of the overlapped bench variant


def bench_sched_pair(circuit, devices, depth=1):
    """Scheduled vs unscheduled vs OVERLAPPED execution of one circuit over
    a device mesh: the comm-aware scheduler's (parallel/scheduler.py) and
    the pipelined executor's (parallel/executor.py) measured row.

    The first two variants run the identical program shape (per-op chain,
    output sharding pinned to the input's so the partitioner cannot
    virtualise trailing permutations into an output-layout drift); the
    third runs the scheduled circuit through the chunked overlapped
    executor.  The row reports the planner-PREDICTED comm savings and
    comm-hidden fraction next to the MEASURED wall-time, compiled-HLO
    collective and async-start deltas.  Value = scheduled-variant amp
    updates/s (validation_only on a CPU mesh, like the other sharded
    configs)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from quest_tpu.analysis.jaxpr_audit import (count_hlo_async_collectives,
                                                count_hlo_collectives)
    from quest_tpu.circuit import _apply_one
    from quest_tpu.obs import global_ledger
    from quest_tpu.parallel import executor as _exec
    from quest_tpu.parallel.scheduler import schedule, schedule_savings

    n = circuit.num_qubits
    nd = len(devices)
    chunks = _SCHED_PAIR_CHUNKS
    sched = schedule(circuit, nd, overlap=True, pipeline_chunks=chunks)
    predicted = schedule_savings(circuit, nd, scheduled=sched,
                                 pipeline_chunks=chunks)
    overlap_pred = _exec.predict_overlap(sched, nd, chunks)
    mesh = Mesh(np.asarray(devices), ("amps",))
    sharding = NamedSharding(mesh, P(None, "amps"))
    measured = {}
    variants = []
    for key, circ in (("unscheduled", circuit), ("scheduled", sched)):
        ops = circ.key()

        def run(s, _ops=ops):
            for _ in range(depth):
                for op in _ops:
                    s = _apply_one(s, op)
            return s

        variants.append((key, jax.jit(run, out_shardings=sharding),
                         len(ops)))
    overlapped_fn = _exec.overlapped_program(sched, nd, chunks, mesh=mesh)
    if depth > 1:
        base = overlapped_fn

        def overlapped_deep(s, _base=base):
            for _ in range(depth):
                s = _base(s)
            return s

        overlapped_fn = overlapped_deep
    variants.append(("overlapped", overlapped_fn, len(sched.ops)))
    for key, fn, n_ops in variants:
        state = jax.device_put(
            jnp.zeros((2, 1 << n), dtype=jnp.float32).at[0, 0].set(1.0),
            sharding)
        text = jax.jit(fn).lower(state).compile().as_text() \
            if key == "overlapped" and depth > 1 \
            else fn.lower(state).compile().as_text()
        colls = _hlo_collective_count(text)
        state_colls = sum(count_hlo_collectives(
            text, min_elems=(1 << n) // nd // 2).values())
        asyncs = count_hlo_async_collectives(text)
        t0 = time.perf_counter()
        out = fn(state)
        out.block_until_ready()  # compile + warm
        compile_s = time.perf_counter() - t0
        from quest_tpu.obs import record_compile
        record_compile(compile_s)
        best = None
        for _ in range(2):
            t0 = time.perf_counter()
            out = fn(state)
            out.block_until_ready()
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        norm = float(jnp.sum(out[0].astype(jnp.float64) ** 2
                             + out[1].astype(jnp.float64) ** 2))
        assert abs(norm - 1.0) < 1e-2, f"norm lost ({key}): {norm}"
        measured[key] = {"seconds": best, "hlo_collectives": colls,
                         "hlo_state_collectives": state_colls,
                         "hlo_async_starts": asyncs["starts"],
                         "hlo_async_separated": asyncs["separated"],
                         "compile_seconds": compile_s,
                         "ops": n_ops}
    un, sc = measured["unscheduled"], measured["scheduled"]
    ov = measured["overlapped"]
    # model-vs-measured ledger row (quest_tpu/obs/ledger.py): predicted
    # model seconds + comm events of the SCHEDULED program next to its
    # measured wall and state-sized compiled collectives — wall drift only
    # judged on TPU platforms (the model is a TPU roofline)
    from quest_tpu.obs import hbm_watermark
    wm = hbm_watermark()
    drift = global_ledger().record(
        f"sched_pair_{n}q_x{nd}", engine="xla", num_devices=nd,
        platform=devices[0].platform,
        predicted_seconds=predicted["model_seconds_after"],
        measured_seconds=sc["seconds"],
        predicted_collectives=predicted["comm_events_after"],
        measured_hlo_collectives=sc["hlo_state_collectives"],
        compile_seconds=sc["compile_seconds"],
        hbm_peak_bytes=(wm or {}).get("peak_bytes_in_use"))
    value = (1 << n) * len(circuit) * depth / sc["seconds"]
    cfg = {
        "qubits": n, "depth": depth, "precision": 1, "devices": nd,
        "platform": devices[0].platform,
        # CPU-mesh pairs validate communication structure, not throughput
        "validation_only": devices[0].platform == "cpu",
        "predicted": {k: predicted[k] for k in (
            "comm_events_before", "comm_events_after",
            "reshard_events_before", "reshard_events_after",
            "comm_bytes_before", "comm_bytes_after",
            "comm_events_saved_frac", "comm_bytes_saved_frac")},
        "measured": {
            "unscheduled_seconds": un["seconds"],
            "scheduled_seconds": sc["seconds"],
            "wall_delta_frac": 1.0 - sc["seconds"] / un["seconds"],
            "unscheduled_hlo_collectives": un["hlo_collectives"],
            "scheduled_hlo_collectives": sc["hlo_collectives"],
            "hlo_collectives_saved": (un["hlo_collectives"]
                                      - sc["hlo_collectives"]),
        },
        # the pipelined-executor columns: model prediction next to the
        # measured wall delta of the SAME scheduled circuit, chunked
        "overlapped": {
            "pipeline_chunks": chunks,
            "predicted_hidden_frac": overlap_pred["predicted_hidden_frac"],
            "model_seconds_serial": overlap_pred["model_seconds_serial"],
            "model_seconds_overlapped":
                overlap_pred["model_seconds_overlapped"],
            "chunked_events": overlap_pred["chunked_events"],
            "hideable_events": overlap_pred["hideable_events"],
            "measured_seconds": ov["seconds"],
            # fraction of the scheduled wall time the chunked pipeline
            # recovered; on a CPU mesh (sync collectives) expect ~0
            "measured_hidden_frac_wall": 1.0 - ov["seconds"] / sc["seconds"],
            "hlo_collectives": ov["hlo_collectives"],
            "hlo_async_starts": ov["hlo_async_starts"],
            "hlo_async_separated": ov["hlo_async_separated"],
        },
        "ops_unscheduled": un["ops"], "ops_scheduled": sc["ops"],
        "model_vs_measured": drift.as_dict(),
    }
    _stamp_counters(cfg, sc["compile_seconds"])
    return value, cfg


def bench_qft22_sched_pair(devices):
    """BASELINE config 5's distributed regime, scheduled: the 22q QFT whose
    trailing bit-reversal the scheduler fuses into one collective."""
    from quest_tpu.circuit import qft_circuit
    return bench_sched_pair(qft_circuit(22), devices)


def bench_random24_sched_pair(devices, depth=2):
    """The 24q random-circuit config over the mesh: 1q gates + CZ ladders
    have no swap networks or wide dense gates, so this row pins the
    scheduler's no-regression contract (predicted savings ~0, unchanged
    wall time) on the headline workload shape."""
    from quest_tpu.circuit import random_circuit
    return bench_sched_pair(random_circuit(24, depth=depth, seed=11),
                            devices, depth=1)


def bench_auto_engine(circuit, n, iters=2, label="auto_engine"):
    """``compile_circuit(engine="auto")`` — the default dispatch — vs the
    forced-XLA variant on the SAME circuit: which backend the planner picks
    (both for the live platform and for TPU-class specs), the epoch
    executor's fused HBM-pass count vs the per-gate count, and measured
    amps/s for both programs.  On CPU auto resolves to the XLA engine
    (Pallas would run in interpret mode) so the two measurements coincide
    and the row documents the spec-level decision; on a chip the auto
    program runs the fused Pallas passes and the ratio is the realized
    engine win (ROADMAP item 2: >= 2x on random/VQE rows)."""
    from quest_tpu.circuit import compile_circuit
    from quest_tpu.parallel import planner

    import jax
    import jax.numpy as jnp

    spec = planner.select_engine(circuit, 1, backend="tpu")
    run_auto = compile_circuit(circuit)               # engine="auto" default
    run_xla = compile_circuit(circuit, engine="xla")

    state = jnp.zeros((2, 1 << n), jnp.float32).at[0, 0].set(1.0)
    compute_a, total, dt, overhead = _run_layered(run_auto, state, iters)
    compile_s = _run_layered.last_compile_seconds
    assert abs(total - 1.0) < 1e-2, f"state not normalised: {total}"
    compute_x, total_x, _, _ = _run_layered(run_xla, state, iters)
    assert abs(total_x - 1.0) < 1e-2, f"state not normalised: {total_x}"

    gates = len(circuit.ops)
    value = (1 << n) * gates * iters / compute_a
    model = spec["model"] or {}
    # model-vs-measured ledger row: the engine model's prediction for the
    # LIVE resolved engine next to the measured per-iteration wall
    from quest_tpu.obs import global_ledger
    live_model = None
    if run_auto.engine == "pallas" and model.get("pallas_seconds"):
        live_model = model["pallas_seconds"] * iters
    elif model.get("xla_seconds"):
        live_model = model["xla_seconds"] * iters
    from quest_tpu.obs import hbm_watermark
    wm = hbm_watermark()
    drift = global_ledger().record(
        f"auto_engine_{n}q", engine=run_auto.engine, num_devices=1,
        platform=jax.devices()[0].platform,
        predicted_seconds=live_model, measured_seconds=compute_a,
        predicted_hbm_passes=model.get("pallas_hbm_passes")
        if run_auto.engine == "pallas" else model.get("xla_hbm_passes"),
        predicted_collectives=0, measured_hlo_collectives=0,
        compile_seconds=compile_s,
        hbm_peak_bytes=(wm or {}).get("peak_bytes_in_use"))
    cfg = {"qubits": n, "gates": gates, "iters": iters, "precision": 1,
           "model_vs_measured": drift.as_dict(),
           "engine_live": run_auto.engine,
           "engine_live_reason": run_auto.engine_reason,
           "engine_tpu_spec": spec["engine"],
           "engine_tpu_spec_reason": spec["reason"],
           "hbm_passes_pallas": model.get("pallas_hbm_passes"),
           "hbm_passes_xla": model.get("xla_hbm_passes"),
           "model_engine_speedup": (
               model["xla_seconds"] / model["pallas_seconds"]
               if model.get("pallas_seconds") else None),
           "amps_per_sec_xla_engine": (1 << n) * gates * iters / compute_x,
           "vs_xla_engine": compute_x / compute_a,
           "seconds": dt, "overhead_seconds": overhead}
    passes = (model.get("pallas_hbm_passes") or gates) \
        if run_auto.engine == "pallas" else gates
    cfg.update(_roofline(1 << n, 1, passes * iters, compute_a))
    _stamp_counters(cfg, compile_s)
    return value, cfg


def bench_random24_auto_engine(n=24, depth=4, iters=2):
    from quest_tpu.circuit import random_circuit
    return bench_auto_engine(random_circuit(n, depth, seed=11), n, iters)


def bench_vqe16_auto_engine(n=16, layers=2, iters=4):
    # n=16 now runs the DEGENERATE single-block geometry (the whole state
    # is one VMEM tile): the ansatz must resolve to the Pallas engine on
    # TPU-class specs as ONE fused pass — the row records pass counts +
    # model speedup where it used to carry the "n>=17 floor" note.
    # Registers below the 10-qubit degenerate floor keep the old XLA
    # behaviour (asserted here so the envelope edge stays truthful).
    from quest_tpu.parallel import planner
    from quest_tpu.serve.selftest import vqe_ansatz
    spec = planner.select_engine(vqe_ansatz(n, layers, seed=0), 1,
                                 backend="tpu")
    assert spec["engine"] == "pallas", spec["reason"]
    assert spec["plan"].hbm_passes == 1, spec["plan"].summary()
    small = planner.select_engine(vqe_ansatz(8, layers, seed=0), 1,
                                  backend="tpu")
    assert small["engine"] == "xla", small["reason"]
    return bench_auto_engine(vqe_ansatz(n, layers, seed=0), n, iters)


def bench_qft(n, precision=1, devices=None):
    """Full QFT pass: H + controlled-phase ladder + reversal swaps — the
    diagonal-gate + swap routing path (BASELINE config 5).  With ``devices``
    the state is sharded over a mesh and the same program exercises
    cross-shard diagonals and all-to-all swap rerouting via GSPMD."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from functools import partial
    from quest_tpu.circuit import _apply_one, qft_circuit

    dtype = jnp.float32 if precision == 1 else jnp.float64
    c = qft_circuit(n)
    gates = len(c)
    c.optimize()
    ops = c.key()

    sharding = None
    if devices is not None:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.asarray(devices), ("amps",))
        sharding = NamedSharding(mesh, P(None, "amps"))

    @partial(jax.jit, static_argnames=("reps",))
    def run(s, reps):
        for _ in range(reps):
            for op in ops:
                s = _apply_one(s, op)
        out = jnp.sum(s[0].astype(jnp.float64) ** 2
                      + s[1].astype(jnp.float64) ** 2)
        return out

    state = jnp.zeros((2, 1 << n), dtype=dtype).at[0, 0].set(1.0)
    if sharding is not None:
        state = jax.device_put(state, sharding)

    comm = None
    if devices is not None:
        # predicted vs measured state-sized collective counts, so CPU-only
        # CI tracks the comm trajectory between TPU rounds (the row used to
        # be validation_only with no comm data at all)
        from quest_tpu.analysis.jaxpr_audit import count_hlo_collectives
        from quest_tpu.parallel import planner as _planner
        predicted = _planner.comm_summary(c, len(devices),
                                          8 if precision == 1 else 16)
        text = run.lower(state, 1).compile().as_text()
        shard_amps = (1 << n) // len(devices)
        by_kind = count_hlo_collectives(text, min_elems=shard_amps // 2)
        comm = {
            "predicted_comm_events": predicted["comm_events"],
            "predicted_reshard_events": predicted["reshard_events"],
            "predicted_bytes_moved": predicted["bytes_moved"],
            "measured_hlo_state_collectives": sum(by_kind.values()),
            "measured_hlo_by_kind": by_kind,
        }

    t0 = time.perf_counter()
    float(run(state, 1))  # compile + warm
    compile_s = time.perf_counter() - t0
    from quest_tpu.obs import record_compile
    record_compile(compile_s)
    float(run(state, 0))  # compile the overhead-probe variant too
    t0 = time.perf_counter()
    base = float(run(state, 0))
    overhead = time.perf_counter() - t0
    t0 = time.perf_counter()
    total = float(run(state, 1))
    dt = time.perf_counter() - t0
    assert abs(total - 1.0) < 1e-2, f"norm lost: {total}"
    compute = max(dt - overhead, 1e-9)
    value = (1 << n) * gates / compute
    cfg = {"qubits": n, "precision": precision, "gates": gates,
           "fused_ops": len(ops), "seconds": dt}
    _stamp_counters(cfg, compile_s)
    if devices is None:
        # roofline fields only for single-chip runs — normalising a virtual
        # CPU-mesh run against the TPU's HBM peak would be meaningless
        cfg.update(_roofline(1 << n, precision, len(ops), compute))
    else:
        cfg["devices"] = len(devices)
        cfg["platform"] = devices[0].platform
        # CPU-mesh configs validate cross-shard communication patterns, not
        # chip throughput: their amps/s is NOT comparable to the baseline
        cfg["validation_only"] = True
        cfg["comm"] = comm
    return value, cfg


# The axon tunnel occasionally drops a remote_compile response mid-read
# (observed: "INTERNAL: ...remote_compile: read body: response body closed
# before all bytes were read"); these signatures mark an attempt as worth
# retrying once.  Deterministic failures (OOM, assertion, compile error)
# don't match and fail immediately — no wall-time wasted re-running them.
_TRANSIENT_SIGNS = ("remote_compile", "read body", "response body",
                    "unavailable", "deadline", "socket", "connection")


def _run_config(fn, *args, **kw):
    """Run one bench config with a single retry on transient tunnel errors.

    Returns ``(value, cfg, errors)``: on success ``errors`` lists any
    swallowed transient failures (also recorded in ``cfg`` as ``retried`` /
    ``retry_error`` so the JSON stays auditable); on failure ``value`` is
    None and ``errors`` carries every attempt's message, root cause first
    (``_run_config.last_exc`` holds the final exception for chaining)."""
    errors = []
    _run_config.last_exc = None
    for _ in range(2):
        try:
            value, cfg = fn(*args, **kw)
            if errors:
                cfg["retried"] = len(errors)
                cfg["retry_error"] = errors[0]
            return value, cfg, errors
        except Exception as e:
            _run_config.last_exc = e
            errors.append(f"{type(e).__name__}: {e}")
            if not any(t in str(e).lower() for t in _TRANSIENT_SIGNS):
                break
    return None, None, errors


def main() -> None:
    import jax

    platform = jax.devices()[0].platform
    n = int(os.environ.get("QUEST_BENCH_QUBITS", "24"))
    depth = int(os.environ.get("QUEST_BENCH_DEPTH", "50"))
    precision = int(os.environ.get("QUEST_BENCH_PRECISION", "1"))
    fuse = os.environ.get("QUEST_BENCH_FUSE", "1") == "1"
    with_matrix = os.environ.get("QUEST_BENCH_MATRIX", "1") == "1"

    # best of 3 timed runs of one compiled program (see _run_layered)
    headline, head_cfg, errors = _run_config(bench_random, n, depth,
                                             precision, fuse, best_of=3)
    if headline is None:
        raise RuntimeError("headline config failed: "
                           + "; then ".join(errors)) from _run_config.last_exc
    head_cfg["platform"] = platform
    head_cfg["provenance"] = _provenance()

    matrix = []

    def add(name, fn, *args, unit="amps/s", **kw):
        value, cfg, errors = _run_config(fn, *args, **kw)
        if value is None:  # a failing config must not kill the headline
            matrix.append({"name": name, "error": "; then ".join(errors)})
        else:
            cfg["provenance"] = _provenance()
            row = {"name": name, "value": value, "unit": unit,
                   "config": cfg}
            if unit == "amps/s":
                row["vs_baseline"] = value / BASELINE_AMPS_PER_SEC
            matrix.append(row)

    if with_matrix:
        if platform != "cpu":
            # a 4 GiB 29q state is chip-sized work; skip on CPU dev boxes
            add("random29_f32_fused", bench_random_big)
            add("random30_f32", bench_random_big30)
        add("random24_f32_unfused", bench_random, n, 10, 1, False)
        add("random24_f64_fused", bench_random, n, depth, 2, True)
        add("random24_f64_unfused", bench_random, n, 10, 2, False)
        add("clifford_t_20q_f64", bench_clifford_t)
        if platform != "cpu":
            add("pauli_expec_26q_f32", bench_pauli_expec)
            add("vmap_batch32_16q_f32", bench_vmap_batch)
            add("trajectories_20q_noisy_f32", bench_trajectories)
        add("densmatr_14q_damping_depol_f32", bench_density, 14, 5, 1)
        # f64 at this size needs the gather engine + per-step donation to fit
        # HBM; depth 3 amortises the 42 per-op dispatches (~5 s/layer on the
        # chip) so the number is not a single-layer sample
        add("densmatr_14q_damping_depol_f64", bench_density, 14, 3, 2)
        # serving subsystem (quest_tpu/serve): 64 tenants, one compile
        add("serve_vqe_16q_batch64", bench_serve_vqe16_batch64)
        # gradient serving (quest_tpu/grad): 64 adjoint gradients, one
        # compile, vs finite differences and unlifted jax.grad
        add("vqe_grad_16q_batch64", bench_vqe_grad_16q_batch64,
            unit="grad/s")
        # numeric-health probes (quest_tpu/obs/numerics.py): instrumented
        # serving must cost <= 5% vs the plain row (asserted in the fn)
        add("serve_vqe_16q_probed_overhead",
            bench_serve_vqe16_probed_overhead, unit="x_probed_over_unprobed")
        # deployment layer (quest_tpu/deploy): 2-replica aggregate
        # throughput vs one service, and the persistent-store cold start
        add("serve_deploy_2replica_rps", bench_serve_deploy_rps,
            unit="req/s")
        add("serve_coldstart_seconds", bench_serve_coldstart,
            unit="x_cold_over_warm")
        # engine dispatch (ops/epoch_pallas.py): default auto engine vs
        # forced XLA, with the planner's spec-level decision recorded
        add("random24_f32_auto_engine", bench_random24_auto_engine)
        add("vqe_16q_auto_engine", bench_vqe16_auto_engine)
        # density noise channels through the auto engine: the 16q-density
        # CEILING decision (outside the [5, 15] density window — and its
        # 4^16-amp state exceeds any single chip) plus a measured
        # in-window Kraus workload (see the fn)
        add("densmatr_16q_kraus_auto_engine", bench_density_kraus_auto)
        add("qft_28q_f32", bench_qft, 28, 1)
        if platform != "cpu":
            add("qft_28q_f32_inplace_ordered", bench_qft_inplace, 28, True)
            add("qft_30q_f32_unordered", bench_qft_inplace, 30, False)
            add("qft_30q_f32_public_api", bench_qft30_api)
        try:
            cpu = jax.devices("cpu")[:_N_VIRT]
        except RuntimeError:
            cpu = []
        if len(cpu) == _N_VIRT:
            add("qft_20q_f32_cpu8shard", bench_qft, 20, 1, cpu)
            # comm-aware scheduler pairs (parallel/scheduler.py): predicted
            # vs measured comm deltas, scheduled and unscheduled in one row
            add("qft_22q_f32_cpu8shard_sched_pair",
                bench_qft22_sched_pair, cpu)
            add("random24_f32_cpu8shard_sched_pair",
                bench_random24_sched_pair, cpu)

    result = {
        "metric": "statevec_1q_gate_amp_updates_per_sec_per_chip",
        "value": headline,
        "unit": "amps/s",
        "vs_baseline": headline / BASELINE_AMPS_PER_SEC,
        "config": head_cfg,
        "matrix": matrix,
    }
    print(json.dumps(result))


def compare_main(argv=None) -> int:
    """``python bench.py --compare`` — the perf-regression gate
    (quest_tpu/obs/regress.py; docs/OBSERVABILITY.md has the tolerance
    table).  Compares the newest usable history round (or ``--current``,
    a raw bench output document or a driver-wrapped capture) against the
    best comparable row of every EARLIER round, prints ONE JSON report,
    and exits 1 iff any gating row regressed past its tolerance."""
    import argparse

    from quest_tpu.obs import regress

    parser = argparse.ArgumentParser(
        prog="python bench.py --compare",
        description="Gate the BENCH_r0*.json perf trajectory.")
    parser.add_argument("--compare", action="store_true",
                        help="(the mode flag that routed here)")
    parser.add_argument("--history", nargs="+", metavar="PATH",
                        help="history files, oldest first (default: the "
                             "repo's BENCH_r*.json)")
    parser.add_argument("--current", metavar="PATH",
                        help="document to gate (default: the newest "
                             "history round that holds any rows)")
    parser.add_argument("--tolerance", type=float,
                        default=regress.DEFAULT_TOLERANCE,
                        help="default allowed fractional regression "
                             "(default %(default)s)")
    parser.add_argument("--row-tolerance", action="append", default=[],
                        metavar="NAME=FRAC", dest="row_tolerance",
                        help="per-row tolerance override; repeatable")
    parser.add_argument("--include-validation", action="store_true",
                        help="let validation_only (CPU-mesh) rows gate too")
    parser.add_argument("--inject", action="append", default=[],
                        metavar="NAME=FACTOR",
                        help="scale a current row's value by FACTOR before "
                             "gating — the CI self-test that proves the "
                             "gate actually fails on a regression")
    parser.add_argument("--out", metavar="FILE",
                        help="also write the report document to FILE (the "
                             "CI workflow artifact)")
    args = parser.parse_args(argv)

    def parse_kv(items, what):
        out = {}
        for item in items:
            name, _, val = item.partition("=")
            if not name or not val:
                parser.error(f"--{what} takes NAME=VALUE, got {item!r}")
            out[name] = float(val)
        return out

    history = regress.load_history(args.history)
    if args.current is not None:
        current = regress.load_round(args.current)
        priors = history
    else:
        usable = [r for r in history if r["rows"]]
        if not usable:
            parser.error("no history round holds any rows")
        current = usable[-1]
        priors = [r for r in history if r["label"] != current["label"]]
    for name, factor in parse_kv(args.inject, "inject").items():
        if name not in current["rows"]:
            parser.error(f"--inject {name}: no such row in "
                         f"{current['label']} (has: "
                         f"{', '.join(sorted(current['rows']))})")
        current["rows"][name]["value"] *= factor
        current["rows"][name]["injected_factor"] = factor
    report = regress.compare(
        current, priors, default_tolerance=args.tolerance,
        row_tolerances=parse_kv(args.row_tolerance, "row-tolerance"),
        include_validation=args.include_validation)
    text = json.dumps(report, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(text + "\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    if "--compare" in sys.argv[1:]:
        sys.exit(compare_main(sys.argv[1:]))
    sys.exit(main())
