"""Benchmark harness: single-qubit-gate amplitude-update throughput per chip.

Workload: a depth-D random circuit (Haar 1-qubit layers + CZ ladders) on an
n-qubit statevector, compiled as ONE fused XLA program per layer and iterated
with buffer donation.  The metric is the reference's headline unit
(BASELINE.md: >=1e8 single-qubit-gate amplitude updates / sec / chip):

    value = 2^n * (#single-qubit gates) / wall_seconds / n_chips

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Env overrides: QUEST_BENCH_QUBITS (default 26 on TPU, 20 on CPU),
QUEST_BENCH_DEPTH (default 8), QUEST_BENCH_PRECISION (1|2, default 1).
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_AMPS_PER_SEC = 1e8  # driver target (BASELINE.md north star)


def main() -> None:
    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    n = int(os.environ.get("QUEST_BENCH_QUBITS", "26" if on_accel else "20"))
    depth = int(os.environ.get("QUEST_BENCH_DEPTH", "8"))
    precision = int(os.environ.get("QUEST_BENCH_PRECISION", "1"))
    dtype = jnp.float32 if precision == 1 else jnp.float64

    from quest_tpu.circuit import compile_circuit, random_circuit

    circuit = random_circuit(n, depth=1, seed=11)
    num_sq_gates_per_layer = n  # the CZ ladder is excluded from the metric
    run_layer = compile_circuit(circuit, donate=True)

    state = jnp.zeros((2, 1 << n), dtype=dtype).at[0, 0].set(1.0)

    # warmup / compile
    state = run_layer(state)
    state.block_until_ready()

    t0 = time.perf_counter()
    for _ in range(depth):
        state = run_layer(state)
    state.block_until_ready()
    dt = time.perf_counter() - t0

    total_sq_gates = depth * num_sq_gates_per_layer
    amps_per_sec = (1 << n) * total_sq_gates / dt
    result = {
        "metric": "statevec_1q_gate_amp_updates_per_sec_per_chip",
        "value": amps_per_sec,
        "unit": "amps/s",
        "vs_baseline": amps_per_sec / BASELINE_AMPS_PER_SEC,
        "config": {"qubits": n, "depth": depth, "precision": precision,
                   "platform": platform, "seconds": dt},
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
