// Gate-fusion engine: the native circuit optimizer behind Circuit.optimize().
//
// Role analogue in the reference: QuEST has no circuit optimizer — every API
// call dispatches its own kernel (ref: QuEST/src/QuEST.c:177-660).  On TPU,
// where every fused gate saves a full HBM pass over the 2^n amplitude array,
// a scheduler that merges gates before compilation is the single cheapest
// performance lever, and it belongs in native code like the reference's
// dispatch layer does.
//
// IR: a flat stream of GateRec records (see fusion.h).  The optimizer makes
// repeated peephole passes:
//   1. adjacent dense 1q gates on the same target merge into one 2x2 product;
//   2. adjacent diagonal gates on identical (targets, controls) merge
//      elementwise;
//   3. self-inverse cancellations (X X -> id, SWAP SWAP -> id);
//   4. commuting sink: a gate may hop left over gates acting on disjoint
//      qubits (and diagonals hop over diagonals on any qubits) to reach a
//      merge partner.
// Passes repeat until a fixed point.
//
// C ABI only (called from Python via ctypes): quest_fuse_circuit takes the
// packed op stream and returns a malloc'd packed stream the caller frees
// with quest_free_buffer.

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>
#include <complex>

namespace {

enum Kind : int32_t {
    KIND_MATRIX = 0,
    KIND_DIAGONAL = 1,
    KIND_X = 2,
    KIND_Y = 3,
    KIND_YCONJ = 4,
    KIND_SWAP = 5,
};

struct Gate {
    int32_t kind;
    std::vector<int32_t> targets;
    std::vector<int32_t> controls;
    std::vector<int32_t> control_states;
    // matrix payload: dense (2*d*d doubles, re-plane then im-plane, d=2^k)
    // or diagonal (2*d doubles)
    std::vector<double> payload;

    bool same_wires(const Gate& o) const {
        return targets == o.targets && controls == o.controls &&
               control_states == o.control_states;
    }
    bool touches(int32_t q) const {
        for (int32_t t : targets) if (t == q) return true;
        for (int32_t c : controls) if (c == q) return true;
        return false;
    }
    bool disjoint(const Gate& o) const {
        for (int32_t t : o.targets) if (touches(t)) return false;
        for (int32_t c : o.controls) if (touches(c)) return false;
        return true;
    }
    bool diagonal_like() const { return kind == KIND_DIAGONAL; }
};

// ---- (de)serialisation ----------------------------------------------------
// Stream layout (all little-endian host types):
//   int64 num_gates
//   per gate:
//     int32 kind, int32 nt, int32 nc, int64 payload_len
//     int32 targets[nt], int32 controls[nc], int32 control_states[nc]
//     double payload[payload_len]

std::vector<Gate> parse(const uint8_t* buf, int64_t len) {
    std::vector<Gate> gates;
    const uint8_t* p = buf;
    const uint8_t* end = buf + len;
    int64_t n;
    std::memcpy(&n, p, 8); p += 8;
    gates.reserve(static_cast<size_t>(n));
    for (int64_t i = 0; i < n && p < end; i++) {
        Gate g;
        int32_t nt, nc; int64_t pl;
        std::memcpy(&g.kind, p, 4); p += 4;
        std::memcpy(&nt, p, 4); p += 4;
        std::memcpy(&nc, p, 4); p += 4;
        std::memcpy(&pl, p, 8); p += 8;
        g.targets.resize(nt);
        if (nt) std::memcpy(g.targets.data(), p, 4 * nt);
        p += 4 * nt;
        g.controls.resize(nc);
        if (nc) std::memcpy(g.controls.data(), p, 4 * nc);
        p += 4 * nc;
        g.control_states.resize(nc);
        if (nc) std::memcpy(g.control_states.data(), p, 4 * nc);
        p += 4 * nc;
        g.payload.resize(pl);
        if (pl) std::memcpy(g.payload.data(), p, 8 * pl);
        p += 8 * pl;
        gates.push_back(std::move(g));
    }
    return gates;
}

std::vector<uint8_t> serialise(const std::vector<Gate>& gates) {
    size_t bytes = 8;
    for (const Gate& g : gates)
        bytes += 4 + 4 + 4 + 8 + 4 * g.targets.size() + 8 * g.controls.size()
               + 8 * g.payload.size();
    std::vector<uint8_t> out(bytes);
    uint8_t* p = out.data();
    int64_t n = static_cast<int64_t>(gates.size());
    std::memcpy(p, &n, 8); p += 8;
    for (const Gate& g : gates) {
        int32_t nt = static_cast<int32_t>(g.targets.size());
        int32_t nc = static_cast<int32_t>(g.controls.size());
        int64_t pl = static_cast<int64_t>(g.payload.size());
        std::memcpy(p, &g.kind, 4); p += 4;
        std::memcpy(p, &nt, 4); p += 4;
        std::memcpy(p, &nc, 4); p += 4;
        std::memcpy(p, &pl, 8); p += 8;
        if (nt) std::memcpy(p, g.targets.data(), 4 * nt);
        p += 4 * nt;
        if (nc) std::memcpy(p, g.controls.data(), 4 * nc);
        p += 4 * nc;
        if (nc) std::memcpy(p, g.control_states.data(), 4 * nc);
        p += 4 * nc;
        if (pl) std::memcpy(p, g.payload.data(), 8 * pl);
        p += 8 * pl;
    }
    return out;
}

// ---- algebra --------------------------------------------------------------

using cd = std::complex<double>;

// payload (2 planes of d*d) -> complex matrix
std::vector<cd> to_complex_mat(const Gate& g, int64_t d) {
    std::vector<cd> m(d * d);
    for (int64_t i = 0; i < d * d; i++)
        m[i] = cd(g.payload[i], g.payload[d * d + i]);
    return m;
}

void from_complex_mat(Gate& g, const std::vector<cd>& m, int64_t d) {
    g.payload.resize(2 * d * d);
    for (int64_t i = 0; i < d * d; i++) {
        g.payload[i] = m[i].real();
        g.payload[d * d + i] = m[i].imag();
    }
}

// b_after * a_first (matrix product: later gate left-multiplies)
bool merge_dense(Gate& first, const Gate& later) {
    if (first.targets.size() != 1 || later.targets.size() != 1) return false;
    std::vector<cd> a = to_complex_mat(first, 2);
    std::vector<cd> b = to_complex_mat(later, 2);
    std::vector<cd> c(4);
    c[0] = b[0] * a[0] + b[1] * a[2];
    c[1] = b[0] * a[1] + b[1] * a[3];
    c[2] = b[2] * a[0] + b[3] * a[2];
    c[3] = b[2] * a[1] + b[3] * a[3];
    from_complex_mat(first, c, 2);
    return true;
}

bool merge_diagonal(Gate& first, const Gate& later) {
    int64_t d = static_cast<int64_t>(first.payload.size()) / 2;
    if (static_cast<int64_t>(later.payload.size()) / 2 != d) return false;
    for (int64_t i = 0; i < d; i++) {
        cd a(first.payload[i], first.payload[d + i]);
        cd b(later.payload[i], later.payload[d + i]);
        cd c = a * b;
        first.payload[i] = c.real();
        first.payload[d + i] = c.imag();
    }
    return true;
}

// promote an X/Y gate (no controls) to its dense 2x2 so it can fuse
void densify(Gate& g) {
    if (g.kind == KIND_X) {
        g.kind = KIND_MATRIX;
        g.payload = {0, 1, 1, 0, 0, 0, 0, 0};
    } else if (g.kind == KIND_Y || g.kind == KIND_YCONJ) {
        double s = (g.kind == KIND_Y) ? 1.0 : -1.0;
        g.kind = KIND_MATRIX;
        g.payload = {0, 0, 0, 0, 0, -s, s, 0};
    } else if (g.kind == KIND_DIAGONAL && g.targets.size() == 1) {
        g.kind = KIND_MATRIX;
        g.payload = {g.payload[0], 0, 0, g.payload[1],
                     g.payload[2], 0, 0, g.payload[3]};
    }
}

bool is_dense_1q_candidate(const Gate& g) {
    // controls allowed: same_wires guarantees both gates share them, and
    // ctrl-U then ctrl-V on identical wires is ctrl-(V*U)
    return g.targets.size() == 1 &&
           (g.kind == KIND_MATRIX || g.kind == KIND_X || g.kind == KIND_Y ||
            g.kind == KIND_YCONJ || g.kind == KIND_DIAGONAL);
}

bool is_identity(const Gate& g) {
    constexpr double eps = 1e-14;
    if (g.kind == KIND_DIAGONAL) {
        int64_t d = static_cast<int64_t>(g.payload.size()) / 2;
        for (int64_t i = 0; i < d; i++)
            if (std::abs(g.payload[i] - 1.0) > eps ||
                std::abs(g.payload[d + i]) > eps) return false;
        return true;
    }
    if (g.kind == KIND_MATRIX) {
        int64_t dd = static_cast<int64_t>(g.payload.size()) / 2;
        int64_t d = 1;
        while (d * d < dd) d++;
        if (d * d != dd) return false;
        for (int64_t r = 0; r < d; r++)
            for (int64_t c = 0; c < d; c++) {
                double want = (r == c) ? 1.0 : 0.0;
                if (std::abs(g.payload[r * d + c] - want) > eps ||
                    std::abs(g.payload[dd + r * d + c]) > eps) return false;
            }
        return true;
    }
    return false;
}

// can `g` hop left over `prev`?
bool commutes_past(const Gate& g, const Gate& prev) {
    if (g.disjoint(prev)) return true;
    // diagonals commute with diagonals regardless of wire overlap
    if (g.diagonal_like() && prev.diagonal_like()) return true;
    return false;
}

bool one_pass(std::vector<Gate>& gates) {
    bool changed = false;
    std::vector<Gate> out;
    out.reserve(gates.size());
    for (Gate& g : gates) {
        bool merged = false;
        // look backwards for a merge partner this gate can reach
        for (int64_t j = static_cast<int64_t>(out.size()) - 1; j >= 0; j--) {
            Gate& cand = out[j];
            // identical-wire merges
            if (cand.same_wires(g)) {
                if (g.kind == KIND_DIAGONAL && cand.kind == KIND_DIAGONAL) {
                    merged = merge_diagonal(cand, g);
                } else if (is_dense_1q_candidate(g) && is_dense_1q_candidate(cand)) {
                    densify(cand); densify(g);
                    merged = merge_dense(cand, g);
                } else if (g.kind == cand.kind &&
                           (g.kind == KIND_X || g.kind == KIND_SWAP)) {
                    out.erase(out.begin() + j);  // self-inverse pair cancels
                    merged = true;
                }
                if (merged) {
                    changed = true;
                    if (j < static_cast<int64_t>(out.size()) &&
                        is_identity(out[j]))
                        out.erase(out.begin() + j);
                }
                break;
            }
            if (!commutes_past(g, cand)) break;
        }
        if (!merged) out.push_back(std::move(g));
    }
    gates = std::move(out);
    return changed;
}

// ---- gate packing ---------------------------------------------------------
// The TPU-specific scheduler pass: kron-merge runs of parallel gates so the
// compiled program applies up to 2^7 = 128 basis states per matmul — one
// lane-aligned MXU contraction and ONE HBM pass where the unpacked circuit
// made k passes.  (The reference has no analogue: its per-gate kernels each
// stream the whole state, QuEST_cpu.c:1688.)

// kron of dense payloads: C = A (x) B where A is the HIGHER target bits
std::vector<double> kron_dense(const std::vector<double>& a, int64_t da,
                               const std::vector<double>& b, int64_t db) {
    int64_t d = da * db;
    std::vector<double> out(2 * d * d, 0.0);
    for (int64_t ar = 0; ar < da; ar++)
        for (int64_t ac = 0; ac < da; ac++) {
            cd av(a[ar * da + ac], a[da * da + ar * da + ac]);
            for (int64_t br = 0; br < db; br++)
                for (int64_t bc = 0; bc < db; bc++) {
                    cd bv(b[br * db + bc], b[db * db + br * db + bc]);
                    cd cv = av * bv;
                    int64_t r = ar * db + br, c = ac * db + bc;
                    out[r * d + c] = cv.real();
                    out[d * d + r * d + c] = cv.imag();
                }
        }
    return out;
}

std::vector<double> kron_diag(const std::vector<double>& a, int64_t da,
                              const std::vector<double>& b, int64_t db) {
    int64_t d = da * db;
    std::vector<double> out(2 * d);
    for (int64_t i = 0; i < da; i++) {
        cd av(a[i], a[da + i]);
        for (int64_t j = 0; j < db; j++) {
            cd bv(b[j], b[db + j]);
            cd cv = av * bv;
            out[i * db + j] = cv.real();
            out[d + i * db + j] = cv.imag();
        }
    }
    return out;
}

// widest uncontrolled diagonal the packer may build: 2^16 entries (1 MiB of
// payload) — wide diagonals are still one broadcast multiply at runtime, but
// the payload must stay bounded (a (n-1)-control phase flip must not
// materialise a state-sized table)
constexpr int64_t kDiagCap = 16;

// rewrite a controlled diagonal as an uncontrolled diagonal over
// (targets..., controls...): entries are the original diag where every
// control bit matches its required state, 1 elsewhere
void absorb_diagonal_controls(Gate& g) {
    if (g.kind != KIND_DIAGONAL || g.controls.empty()) return;
    if (static_cast<int64_t>(g.targets.size() + g.controls.size()) > kDiagCap)
        return;  // keep controlled form rather than blow up the payload
    int64_t dt = static_cast<int64_t>(g.payload.size()) / 2;
    int64_t nc = static_cast<int64_t>(g.controls.size());
    int64_t d = dt << nc;
    std::vector<double> out(2 * d);
    for (int64_t i = 0; i < d; i++) {
        int64_t tbits = i % dt;
        int64_t cbits = i / dt;
        bool active = true;
        for (int64_t c = 0; c < nc; c++)
            if (((cbits >> c) & 1) != g.control_states[c]) active = false;
        cd v = active ? cd(g.payload[tbits], g.payload[dt + tbits]) : cd(1.0, 0.0);
        out[i] = v.real();
        out[d + i] = v.imag();
    }
    for (int64_t c = 0; c < nc; c++) g.targets.push_back(g.controls[c]);
    g.controls.clear();
    g.control_states.clear();
    g.payload = std::move(out);
}

// positions of each g target within pack.targets, or empty if not a subset
std::vector<int64_t> subset_positions(const Gate& g, const Gate& pack) {
    std::vector<int64_t> pos;
    for (int32_t t : g.targets) {
        int64_t p = -1;
        for (size_t i = 0; i < pack.targets.size(); i++)
            if (pack.targets[i] == t) { p = static_cast<int64_t>(i); break; }
        if (p < 0) return {};
        pos.push_back(p);
    }
    return pos;
}

// a diagonal AFTER a dense pack whose targets cover it: left-multiply = scale
// each matrix row by the diagonal entry of that row's bits
bool diag_into_dense(Gate& pack, const Gate& g) {
    std::vector<int64_t> pos = subset_positions(g, pack);
    if (pos.empty() && !g.targets.empty()) return false;
    int64_t d = int64_t{1} << pack.targets.size();
    int64_t dg = static_cast<int64_t>(g.payload.size()) / 2;
    for (int64_t r = 0; r < d; r++) {
        int64_t gi = 0;
        for (size_t b = 0; b < pos.size(); b++)
            gi |= ((r >> pos[b]) & 1) << b;
        cd f(g.payload[gi], g.payload[dg + gi]);
        for (int64_t c = 0; c < d; c++) {
            cd v(pack.payload[r * d + c], pack.payload[d * d + r * d + c]);
            v *= f;
            pack.payload[r * d + c] = v.real();
            pack.payload[d * d + r * d + c] = v.imag();
        }
    }
    return true;
}

// a dense 1q gate AFTER a pack that contains its target: pack = (I⊗g⊗I)·pack
bool dense1q_into_pack(Gate& pack, const Gate& g) {
    std::vector<int64_t> pos = subset_positions(g, pack);
    if (pos.size() != 1) return false;
    int64_t p = pos[0];
    int64_t d = int64_t{1} << pack.targets.size();
    std::vector<cd> m = to_complex_mat(pack, d);
    std::vector<cd> gm = to_complex_mat(g, 2);
    for (int64_t r = 0; r < d; r++) {
        if ((r >> p) & 1) continue;
        int64_t r1 = r | (int64_t{1} << p);
        for (int64_t c = 0; c < d; c++) {
            cd a = m[r * d + c], b = m[r1 * d + c];
            m[r * d + c] = gm[0] * a + gm[1] * b;
            m[r1 * d + c] = gm[2] * a + gm[3] * b;
        }
    }
    from_complex_mat(pack, m, d);
    return true;
}

// merge diagonal g into diagonal pack over the UNION of their targets
bool merge_diag_union(Gate& pack, const Gate& g, int64_t cap) {
    std::vector<int32_t> u = pack.targets;
    for (int32_t t : g.targets) {
        bool found = false;
        for (int32_t x : u) if (x == t) { found = true; break; }
        if (!found) u.push_back(t);
    }
    if (static_cast<int64_t>(u.size()) > cap) return false;
    std::vector<int64_t> gp;
    for (int32_t t : g.targets)
        for (size_t i = 0; i < u.size(); i++)
            if (u[i] == t) { gp.push_back(static_cast<int64_t>(i)); break; }
    int64_t d = int64_t{1} << u.size();
    int64_t dp = static_cast<int64_t>(pack.payload.size()) / 2;
    int64_t dg = static_cast<int64_t>(g.payload.size()) / 2;
    std::vector<double> outp(2 * d);
    for (int64_t i = 0; i < d; i++) {
        int64_t pi = i & (dp - 1);  // pack targets are the low union bits
        int64_t gi = 0;
        for (size_t b = 0; b < gp.size(); b++)
            gi |= ((i >> gp[b]) & 1) << b;
        cd v = cd(pack.payload[pi], pack.payload[dp + pi])
             * cd(g.payload[gi], g.payload[dg + gi]);
        outp[i] = v.real();
        outp[d + i] = v.imag();
    }
    pack.targets = std::move(u);
    pack.payload = std::move(outp);
    return true;
}

// pack runs of parallel uncontrolled gates into multi-target gates: dense
// packs of <= max_pack qubits (one MXU contraction each), diagonal packs of
// <= kDiagCap qubits (one broadcast multiply each).  A gate scans BACKWARDS
// over gates it commutes past (disjoint wires; diagonals additionally hop
// any diagonal) so e.g. the CZ ladder of a brickwork layer folds into the
// dense packs of the same layer — row scalings, costing zero extra HBM
// passes at runtime.
void pack_pass(std::vector<Gate>& gates, int32_t max_pack) {
    std::vector<Gate> out;
    out.reserve(gates.size());

    auto find_merge = [&](Gate& g) -> bool {
        if (!g.controls.empty()) return false;
        // A lone 1q diagonal prefers joining a diagonal pack (stays a cheap
        // broadcast multiply); failing that it densifies into the nearest
        // disjoint dense pack it commuted past, recorded here.
        int64_t dense_fallback = -1;
        for (int64_t j = static_cast<int64_t>(out.size()) - 1; j >= 0; j--) {
            Gate& cand = out[j];
            bool open = cand.controls.empty();
            if (g.kind == KIND_DIAGONAL) {
                if (open && cand.kind == KIND_MATRIX &&
                    diag_into_dense(cand, g))
                    return true;
                if (open && cand.kind == KIND_DIAGONAL &&
                    merge_diag_union(cand, g, kDiagCap))
                    return true;
                if (open && cand.kind == KIND_MATRIX && dense_fallback < 0 &&
                    g.targets.size() == 1 && g.disjoint(cand) &&
                    static_cast<int32_t>(cand.targets.size()) + 1 <= max_pack)
                    dense_fallback = j;
                if (cand.diagonal_like() || g.disjoint(cand))
                    continue;  // hop: commutes past
                break;
            }
            if (g.kind == KIND_MATRIX) {
                if (open && cand.kind == KIND_MATRIX) {
                    if (g.targets.size() == 1 && dense1q_into_pack(cand, g))
                        return true;
                    if (g.disjoint(cand) &&
                        static_cast<int32_t>(cand.targets.size()
                                             + g.targets.size()) <= max_pack) {
                        // g's targets become the HIGH bits: targets list
                        // order is least-significant-first
                        int64_t dl = int64_t{1} << cand.targets.size();
                        int64_t dg = int64_t{1} << g.targets.size();
                        cand.payload = kron_dense(g.payload, dg,
                                                  cand.payload, dl);
                        for (int32_t t : g.targets) cand.targets.push_back(t);
                        return true;
                    }
                }
                if (g.disjoint(cand)) continue;  // hop
                return false;
            }
            return false;
        }
        if (g.kind == KIND_DIAGONAL && dense_fallback >= 0) {
            // densify the 1q diagonal and kron it onto the recorded pack
            // (valid: g commuted past everything to the pack's right)
            densify(g);
            Gate& cand = out[dense_fallback];
            int64_t dl = int64_t{1} << cand.targets.size();
            cand.payload = kron_dense(g.payload, 2, cand.payload, dl);
            cand.targets.push_back(g.targets[0]);
            return true;
        }
        return false;
    };

    for (Gate& g : gates) {
        if (g.controls.empty() &&
            (g.kind == KIND_X || g.kind == KIND_Y || g.kind == KIND_YCONJ))
            densify(g);
        if (g.kind == KIND_DIAGONAL) absorb_diagonal_controls(g);
        if ((g.kind == KIND_MATRIX || g.kind == KIND_DIAGONAL) &&
            g.controls.empty()) {
            if (find_merge(g)) continue;
        }
        out.push_back(std::move(g));
    }
    gates = std::move(out);
}

}  // namespace

extern "C" {

// Fuse the packed circuit; returns a malloc'd packed stream (caller frees
// with quest_free_buffer) and writes its length to *out_len.  max_pack > 1
// additionally kron-packs runs of parallel gates into multi-target gates of
// up to that many qubits (7 = 128 lanes, the f32 MXU tile width).
uint8_t* quest_fuse_circuit(const uint8_t* buf, int64_t len, int64_t* out_len,
                            int32_t max_pack) {
    std::vector<Gate> gates = parse(buf, len);
    for (int pass = 0; pass < 32; pass++)
        if (!one_pass(gates)) break;
    if (max_pack > 1)
        pack_pass(gates, max_pack);
    std::vector<uint8_t> out = serialise(gates);
    uint8_t* result = static_cast<uint8_t*>(std::malloc(out.size()));
    std::memcpy(result, out.data(), out.size());
    *out_len = static_cast<int64_t>(out.size());
    return result;
}

void quest_free_buffer(uint8_t* buf) { std::free(buf); }

int64_t quest_fusion_abi_version() { return 3; }

}  // extern "C"
