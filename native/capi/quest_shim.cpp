// C front-end implementation: bridges the full QuEST-compatible C API
// (quest_tpu_c.h) onto the quest_tpu Python/JAX runtime via an embedded
// CPython interpreter.
//
// Architecture: the reference links user C programs against native kernels
// directly (libQuEST.so); here the "kernels" are XLA programs managed by the
// Python runtime, so the shim owns an interpreter, imports quest_tpu once,
// and forwards each C call.  Handles in the public structs are PyObject
// pointers.  Argument tuples are built with Py_BuildValue ("N" consumes the
// reference of every freshly-built object, so nothing leaks per call).
//
// Validation errors raised Python-side (quest_tpu.QuESTError) are routed
// through the weak symbol invalidQuESTInputError — exactly the reference's
// test hook (ref: QuEST_validation.c:175-178): the default prints and exits,
// and a test binary may override it with a throwing definition.

#include "quest_tpu_c.h"

#include <Python.h>

#include <cstdarg>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

PyObject* g_module = nullptr;

PyObject* mod() {
    if (!g_module) {
        if (!Py_IsInitialized()) {
            Py_Initialize();
        }
        g_module = PyImport_ImportModule("quest_tpu");
        if (!g_module) {
            PyErr_Print();
            std::fprintf(stderr, "quest_tpu_c: cannot import quest_tpu\n");
            std::exit(1);
        }
    }
    return g_module;
}

// Route a pending Python exception through the invalidQuESTInputError hook
// (QuESTError) or print-and-exit (anything else).  If the hook returns
// normally the failed operation is skipped, mirroring the reference's
// weak-symbol contract.
void handle_python_error() {
    if (!PyErr_Occurred()) return;
    PyObject *type, *value, *tb;
    PyErr_Fetch(&type, &value, &tb);
    PyErr_NormalizeException(&type, &value, &tb);

    PyObject* qe_cls = PyObject_GetAttrString(mod(), "QuESTError");
    bool is_quest = qe_cls && value &&
                    PyObject_IsInstance(value, qe_cls) == 1;
    Py_XDECREF(qe_cls);
    PyErr_Clear();

    if (!is_quest) {
        PyErr_Restore(type, value, tb);
        PyErr_Print();
        std::exit(1);
    }

    // static: an overriding hook may `throw errMsg` (the reference's own
    // tests/main.cpp:27-29 does) and the pointer must outlive this frame
    static char msg[1024];
    static char func[256];
    std::snprintf(msg, sizeof msg, "unknown error");
    func[0] = '\0';
    PyObject* m = PyObject_GetAttrString(value, "message");
    if (m) {
        const char* s = PyUnicode_AsUTF8(m);
        if (s) std::snprintf(msg, sizeof msg, "%s", s);
        Py_DECREF(m);
    }
    PyErr_Clear();
    PyObject* f = PyObject_GetAttrString(value, "func");
    if (f) {
        if (f != Py_None) {
            const char* s = PyUnicode_AsUTF8(f);
            if (s) std::snprintf(func, sizeof func, "%s", s);
        }
        Py_DECREF(f);
    }
    PyErr_Clear();
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
    invalidQuESTInputError(msg, func);  // may exit, may throw, may return
}

// call quest_tpu.<name>(args...); fmt is a Py_BuildValue tuple format like
// "(Nid)" (nullptr fmt = no arguments).  Returns a new reference, or nullptr
// if a validation error was routed through a returning hook.
PyObject* pycall(const char* name, const char* fmt, ...) {
    std::fflush(stdout);
    PyObject* args = nullptr;
    if (fmt) {
        va_list va;
        va_start(va, fmt);
        args = Py_VaBuildValue(fmt, va);
        va_end(va);
        if (!args) { handle_python_error(); return nullptr; }
    }
    PyObject* fn = PyObject_GetAttrString(mod(), name);
    if (!fn) { Py_XDECREF(args); handle_python_error(); return nullptr; }
    PyObject* result = PyObject_CallObject(fn, args);
    Py_DECREF(fn);
    Py_XDECREF(args);
    if (!result) handle_python_error();
    PyRun_SimpleString("import sys; sys.stdout.flush()");
    return result;
}

void drop(PyObject* o) { Py_XDECREF(o); }

const char* kCannotFit =
    "The specified matrix targets too many qubits; the batches of amplitudes "
    "to modify cannot all fit in a single distributed node's memory.";

// ref: validateMultiQubitMatrixFitsInNode — the C struct's chunk size is
// authoritative (the reference's tests modify qureg.numAmpsPerChunk
// directly to provoke this error)
bool fits_ok(Qureg q, int numTargs, const char* func) {
    // invalid counts are reported by runtime validation first (the
    // reference validates targets before the fits-in-node rule)
    int max_targs = q.numQubitsRepresented * (q.isDensityMatrix ? 2 : 1);
    if (numTargs <= 0 || numTargs > max_targs) return true;
    if ((1LL << numTargs) <= q.numAmpsPerChunk) return true;
    invalidQuESTInputError(kCannotFit, func);
    return false;
}

const char* kMatrixNotInit =
    "The ComplexMatrixN was not successfully created (possibly insufficient "
    "memory available).";

// ref analogue: validateMatrixInit — an un-created ComplexMatrixN (NULL
// arrays) must raise rather than be dereferenced
bool matrixN_ok(ComplexMatrixN u, const char* func) {
    if (u.real && u.imag) return true;
    invalidQuESTInputError(kMatrixNotInit, func);
    return false;  // hook returned: skip the operation
}

double to_double(PyObject* o) {
    if (!o) return 0.0;
    double v = PyFloat_AsDouble(o);
    if (PyErr_Occurred()) { PyErr_Clear(); v = 0.0; }
    Py_DECREF(o);
    return v;
}

long long to_ll(PyObject* o) {
    if (!o) return 0;
    long long v = PyLong_AsLongLong(o);
    if (PyErr_Occurred()) { PyErr_Clear(); v = 0; }
    Py_DECREF(o);
    return v;
}

Complex to_cmplx(PyObject* o) {
    Complex c = {0.0, 0.0};
    if (!o) return c;
    c.real = PyComplex_RealAsDouble(o);
    c.imag = PyComplex_ImagAsDouble(o);
    if (PyErr_Occurred()) { PyErr_Clear(); c.real = c.imag = 0.0; }
    Py_DECREF(o);
    return c;
}

// ---- object builders (each returns a NEW reference; pass with "N") --------

PyObject* qh(Qureg q) {
    PyObject* h = static_cast<PyObject*>(q.handle);
    Py_INCREF(h);
    return h;
}

PyObject* eh(QuESTEnv env) {
    PyObject* h = static_cast<PyObject*>(env.handle);
    Py_INCREF(h);
    return h;
}

PyObject* dh(DiagonalOp op) {
    PyObject* h = static_cast<PyObject*>(op.handle);
    Py_INCREF(h);
    return h;
}

PyObject* int_list(const int* xs, long long n) {
    if (n < 0 || !xs) n = 0;  // invalid counts/arrays: runtime validation rejects
    PyObject* list = PyList_New(n);
    for (long long i = 0; i < n; i++)
        PyList_SET_ITEM(list, i, PyLong_FromLong(xs[i]));
    return list;
}

PyObject* pauli_list(const enum pauliOpType* xs, long long n) {
    if (n < 0 || !xs) n = 0;
    PyObject* list = PyList_New(n);
    for (long long i = 0; i < n; i++)
        PyList_SET_ITEM(list, i, PyLong_FromLong(static_cast<long>(xs[i])));
    return list;
}

PyObject* double_list(const qreal* xs, long long n) {
    if (n < 0 || !xs) n = 0;
    PyObject* list = PyList_New(n);
    for (long long i = 0; i < n; i++)
        PyList_SET_ITEM(list, i, PyFloat_FromDouble(xs[i]));
    return list;
}

PyObject* cobj(Complex c) { return PyComplex_FromDoubles(c.real, c.imag); }

PyObject* vec_obj(Vector v) {
    return Py_BuildValue("(ddd)", v.x, v.y, v.z);
}

template <typename M>
PyObject* mat_obj_dim(const M& u, int dim) {
    PyObject* rows = PyList_New(dim);
    for (int r = 0; r < dim; r++) {
        PyObject* row = PyList_New(dim);
        for (int c = 0; c < dim; c++)
            PyList_SET_ITEM(row, c, PyComplex_FromDoubles(u.real[r][c],
                                                          u.imag[r][c]));
        PyList_SET_ITEM(rows, r, row);
    }
    return rows;
}

PyObject* m2(ComplexMatrix2 u) { return mat_obj_dim(u, 2); }
PyObject* m4(ComplexMatrix4 u) { return mat_obj_dim(u, 4); }

PyObject* mN(ComplexMatrixN u) {
    // pack both planes into one bytes object and rebuild numpy-side:
    // O(1) Python objects per matrix (a 2^10-wide Kraus superoperator would
    // otherwise cost ~2M element objects)
    int64_t dim = 1LL << u.numQubits;
    PyObject* bytes = PyBytes_FromStringAndSize(
        nullptr, (Py_ssize_t)(2 * dim * dim * 8));
    if (!bytes) return nullptr;
    char* p = PyBytes_AS_STRING(bytes);
    for (int64_t r = 0; r < dim; r++)
        std::memcpy(p + r * dim * 8, u.real[r], dim * 8);
    for (int64_t r = 0; r < dim; r++)
        std::memcpy(p + (dim + r) * dim * 8, u.imag[r], dim * 8);
    return pycall("_matrix_from_buffer", "(iN)", u.numQubits, bytes);
}

PyObject* m2_list(const ComplexMatrix2* ops, int n) {
    if (n < 0 || !ops) n = 0;  // runtime validation rejects the bad count
    PyObject* list = PyList_New(n);
    for (int i = 0; i < n; i++) PyList_SET_ITEM(list, i, m2(ops[i]));
    return list;
}

PyObject* m4_list(const ComplexMatrix4* ops, int n) {
    if (n < 0 || !ops) n = 0;  // runtime validation rejects the bad count
    PyObject* list = PyList_New(n);
    for (int i = 0; i < n; i++) PyList_SET_ITEM(list, i, m4(ops[i]));
    return list;
}

PyObject* mN_list(const ComplexMatrixN* ops, int n) {
    if (n < 0 || !ops) n = 0;  // runtime validation rejects the bad count
    PyObject* list = PyList_New(n);
    for (int i = 0; i < n; i++) PyList_SET_ITEM(list, i, mN(ops[i]));
    return list;
}

// build a Python PauliHamil mirroring the C struct's current arrays
PyObject* hamil_obj(PauliHamil h) {
    PyObject* ph = pycall("createPauliHamil", "(ii)", h.numQubits, h.numSumTerms);
    if (!ph) return nullptr;
    drop(pycall("initPauliHamil", "(ONN)", ph,
                double_list(h.termCoeffs, h.numSumTerms),
                pauli_list(h.pauliCodes,
                           (long long)h.numSumTerms * h.numQubits)));
    return ph;
}

// copy a (2, numAmps) float64 numpy buffer into a ComplexArray mirror
void fill_state_mirror(PyObject* buf, ComplexArray dst, long long numAmps) {
    if (!buf || !dst.real) { drop(buf); return; }
    Py_buffer view;
    if (PyObject_GetBuffer(buf, &view, PyBUF_C_CONTIGUOUS) == 0) {
        const double* d = static_cast<const double*>(view.buf);
        std::memcpy(dst.real, d, sizeof(double) * numAmps);
        std::memcpy(dst.imag, d + numAmps, sizeof(double) * numAmps);
        PyBuffer_Release(&view);
    } else {
        PyErr_Clear();
    }
    drop(buf);
}

}  // namespace

extern "C" {

// default hook: print and exit, like the reference (QuEST_validation.c:167-178)
__attribute__((weak)) void invalidQuESTInputError(const char* errMsg,
                                                  const char* errFunc) {
    std::printf("!!!\n");
    std::printf("QuEST Error in function %s: %s\n", errFunc, errMsg);
    std::printf("!!!\n");
    std::printf("exiting..\n");
    std::exit(1);
}

/* ---- environment ------------------------------------------------------- */

QuESTEnv createQuESTEnv(void) {
    PyObject* env = pycall("createQuESTEnv", nullptr);
    QuESTEnv out;
    out.rank = 0;
    out.numRanks = 1;
    if (env) {
        PyObject* nr = PyObject_GetAttrString(env, "num_ranks");
        if (nr) out.numRanks = static_cast<int>(PyLong_AsLong(nr));
        Py_XDECREF(nr);
        PyErr_Clear();
    }
    out.handle = env;
    return out;
}

void destroyQuESTEnv(QuESTEnv env) {
    Py_XDECREF(static_cast<PyObject*>(env.handle));
}

void syncQuESTEnv(QuESTEnv env) { drop(pycall("syncQuESTEnv", "(N)", eh(env))); }

int syncQuESTSuccess(int successCode) {
    // single-controller SPMD: no cross-rank agreement needed
    // (ref: Allreduce(LAND), QuEST_cpu_distributed.c:166-170)
    return successCode;
}

void reportQuESTEnv(QuESTEnv env) { drop(pycall("reportQuESTEnv", "(N)", eh(env))); }

void getEnvironmentString(QuESTEnv env, Qureg qureg, char str[200]) {
    PyObject* s = pycall("getEnvironmentString", "(NN)", eh(env), qh(qureg));
    str[0] = '\0';
    if (s) {
        const char* c = PyUnicode_AsUTF8(s);
        if (c) std::snprintf(str, 200, "%s", c);
        PyErr_Clear();
        Py_DECREF(s);
    }
}

void seedQuEST(unsigned long int* seedArray, int numSeeds) {
    PyObject* list = PyList_New(numSeeds);
    for (int i = 0; i < numSeeds; i++)
        PyList_SET_ITEM(list, i, PyLong_FromUnsignedLong(seedArray[i]));
    drop(pycall("seedQuEST", "(Ni)", list, numSeeds));
}

void seedQuESTDefault(void) { drop(pycall("seedQuESTDefault", nullptr)); }

/* ---- registers --------------------------------------------------------- */

static Qureg make_qureg(PyObject* q, int numQubits, int isDensity) {
    Qureg out;
    out.isDensityMatrix = isDensity;
    out.numQubitsRepresented = numQubits;
    out.numQubitsInStateVec = numQubits * (isDensity ? 2 : 1);
    out.numAmpsTotal = 1LL << out.numQubitsInStateVec;
    out.numAmpsPerChunk = out.numAmpsTotal;
    out.chunkId = 0;
    out.numChunks = 1;
    // host SoA mirror, the reference's own memory model (16 B/amp at f64,
    // ref: QuEST_cpu.c:1279-1315); filled on demand by copyStateFromGPU
    out.stateVec.real = static_cast<qreal*>(
        std::malloc(sizeof(qreal) * out.numAmpsTotal));
    out.stateVec.imag = static_cast<qreal*>(
        std::malloc(sizeof(qreal) * out.numAmpsTotal));
    out.pairStateVec.real = nullptr;
    out.pairStateVec.imag = nullptr;
    out.handle = q;
    return out;
}

Qureg createQureg(int numQubits, QuESTEnv env) {
    // validate against the C struct's rank count first: user programs (and
    // the reference tests) may have modified env.numRanks directly
    drop(pycall("_validate_create_qureg", "(iii)", numQubits, env.numRanks, 0));
    PyObject* q = pycall("createQureg", "(iN)", numQubits, eh(env));
    return make_qureg(q, numQubits, 0);
}

Qureg createDensityQureg(int numQubits, QuESTEnv env) {
    drop(pycall("_validate_create_qureg", "(iii)", numQubits, env.numRanks, 1));
    PyObject* q = pycall("createDensityQureg", "(iN)", numQubits, eh(env));
    return make_qureg(q, numQubits, 1);
}

Qureg createCloneQureg(Qureg qureg, QuESTEnv env) {
    PyObject* q = pycall("createCloneQureg", "(NN)", qh(qureg), eh(env));
    return make_qureg(q, qureg.numQubitsRepresented, qureg.isDensityMatrix);
}

void destroyQureg(Qureg qureg, QuESTEnv env) {
    (void)env;
    drop(pycall("destroyQureg", "(N)", qh(qureg)));
    std::free(qureg.stateVec.real);
    std::free(qureg.stateVec.imag);
    Py_XDECREF(static_cast<PyObject*>(qureg.handle));
}

void cloneQureg(Qureg targetQureg, Qureg copyQureg) {
    drop(pycall("cloneQureg", "(NN)", qh(targetQureg), qh(copyQureg)));
}

int getNumQubits(Qureg qureg) { return qureg.numQubitsRepresented; }

long long int getNumAmps(Qureg qureg) {
    return to_ll(pycall("getNumAmps", "(N)", qh(qureg)));
}

void reportQuregParams(Qureg q) { drop(pycall("reportQuregParams", "(N)", qh(q))); }
void reportState(Qureg q) { drop(pycall("reportState", "(N)", qh(q))); }

void reportStateToScreen(Qureg q, QuESTEnv env, int reportRank) {
    drop(pycall("reportStateToScreen", "(NNi)", qh(q), eh(env), reportRank));
}

void copyStateToGPU(Qureg q) {
    // push the host mirror into the device state (ref: QuEST_gpu.cu:451-460)
    if (!q.stateVec.real) return;
    if (q.isDensityMatrix)
        drop(pycall("setDensityAmps", "(NNN)", qh(q),
                    double_list(q.stateVec.real, q.numAmpsTotal),
                    double_list(q.stateVec.imag, q.numAmpsTotal)));
    else
        drop(pycall("initStateFromAmps", "(NNN)", qh(q),
                    double_list(q.stateVec.real, q.numAmpsTotal),
                    double_list(q.stateVec.imag, q.numAmpsTotal)));
}

void copyStateFromGPU(Qureg q) {
    // pull the device state into the host mirror (ref: QuEST_gpu.cu:462-473)
    fill_state_mirror(pycall("_amps_buffer", "(N)", qh(q)), q.stateVec,
                      q.numAmpsTotal);
}

/* ---- matrices & operator structs --------------------------------------- */

ComplexMatrixN createComplexMatrixN(int numQubits) {
    // runtime-side validation (throws via the hook on numQubits < 1)
    drop(pycall("createComplexMatrixN", "(i)", numQubits));
    int dim = numQubits >= 1 ? 1 << numQubits : 1;
    ComplexMatrixN m;
    m.numQubits = numQubits;
    m.real = static_cast<qreal**>(std::calloc(dim, sizeof(qreal*)));
    m.imag = static_cast<qreal**>(std::calloc(dim, sizeof(qreal*)));
    for (int r = 0; r < dim; r++) {
        m.real[r] = static_cast<qreal*>(std::calloc(dim, sizeof(qreal)));
        m.imag[r] = static_cast<qreal*>(std::calloc(dim, sizeof(qreal)));
    }
    return m;
}

void destroyComplexMatrixN(ComplexMatrixN m) {
    if (!matrixN_ok(m, "destroyComplexMatrixN")) return;
    int dim = 1 << m.numQubits;
    for (int r = 0; r < dim; r++) {
        std::free(m.real[r]);
        std::free(m.imag[r]);
    }
    std::free(m.real);
    std::free(m.imag);
}

// C declaration uses VLA types (see header); ABI-compatible flat definition
void initComplexMatrixN(ComplexMatrixN m, qreal* real, qreal* imag) {
    if (!matrixN_ok(m, "initComplexMatrixN")) return;
    int dim = 1 << m.numQubits;
    for (int r = 0; r < dim; r++)
        for (int c = 0; c < dim; c++) {
            m.real[r][c] = real[r * dim + c];
            m.imag[r][c] = imag[r * dim + c];
        }
}

ComplexMatrixN bindArraysToStackComplexMatrixN(
        int numQubits, qreal* re, qreal* im,
        qreal** reStorage, qreal** imStorage) {
    int dim = 1 << numQubits;
    for (int r = 0; r < dim; r++) {
        reStorage[r] = re + r * dim;
        imStorage[r] = im + r * dim;
    }
    ComplexMatrixN m;
    m.numQubits = numQubits;
    m.real = reStorage;
    m.imag = imStorage;
    return m;
}

PauliHamil createPauliHamil(int numQubits, int numSumTerms) {
    // route through the runtime purely for validation (throws via the hook
    // on non-positive dims, ref: validateHamilParams)
    drop(pycall("createPauliHamil", "(ii)", numQubits, numSumTerms));
    PauliHamil h;
    h.numQubits = numQubits;
    h.numSumTerms = numSumTerms;
    h.pauliCodes = static_cast<enum pauliOpType*>(
        std::calloc((size_t)numSumTerms * numQubits, sizeof(enum pauliOpType)));
    h.termCoeffs = static_cast<qreal*>(
        std::calloc(numSumTerms, sizeof(qreal)));
    return h;
}

void destroyPauliHamil(PauliHamil h) {
    std::free(h.pauliCodes);
    std::free(h.termCoeffs);
}

PauliHamil createPauliHamilFromFile(char* fn) {
    PauliHamil h = {nullptr, nullptr, 0, 0};
    PyObject* ph = pycall("createPauliHamilFromFile", "(s)", fn);
    if (!ph) return h;
    PyObject* pair = pycall("_hamil_buffers", "(O)", ph);
    PyObject* nq = PyObject_GetAttrString(ph, "num_qubits");
    PyObject* nt = PyObject_GetAttrString(ph, "num_sum_terms");
    h.numQubits = nq ? static_cast<int>(PyLong_AsLong(nq)) : 0;
    h.numSumTerms = nt ? static_cast<int>(PyLong_AsLong(nt)) : 0;
    Py_XDECREF(nq);
    Py_XDECREF(nt);
    PyErr_Clear();
    h.pauliCodes = static_cast<enum pauliOpType*>(
        std::calloc((size_t)h.numSumTerms * h.numQubits,
                    sizeof(enum pauliOpType)));
    h.termCoeffs = static_cast<qreal*>(std::calloc(h.numSumTerms, sizeof(qreal)));
    if (pair && PyTuple_Check(pair) && PyTuple_Size(pair) == 2) {
        Py_buffer cv, fv;
        if (PyObject_GetBuffer(PyTuple_GetItem(pair, 0), &cv,
                               PyBUF_C_CONTIGUOUS) == 0) {
            const int* codes = static_cast<const int*>(cv.buf);
            for (long long i = 0; i < (long long)h.numSumTerms * h.numQubits; i++)
                h.pauliCodes[i] = static_cast<enum pauliOpType>(codes[i]);
            PyBuffer_Release(&cv);
        } else PyErr_Clear();
        if (PyObject_GetBuffer(PyTuple_GetItem(pair, 1), &fv,
                               PyBUF_C_CONTIGUOUS) == 0) {
            std::memcpy(h.termCoeffs, fv.buf, sizeof(qreal) * h.numSumTerms);
            PyBuffer_Release(&fv);
        } else PyErr_Clear();
    }
    drop(pair);
    drop(ph);
    return h;
}

void initPauliHamil(PauliHamil h, qreal* coeffs, enum pauliOpType* codes) {
    // runtime-side validation first (throws via the hook on invalid codes)
    PyObject* ph = pycall("createPauliHamil", "(ii)", h.numQubits, h.numSumTerms);
    if (ph) {
        drop(pycall("initPauliHamil", "(ONN)", ph,
                    double_list(coeffs, h.numSumTerms),
                    pauli_list(codes, (long long)h.numSumTerms * h.numQubits)));
        drop(ph);
    }
    std::memcpy(h.termCoeffs, coeffs, sizeof(qreal) * h.numSumTerms);
    std::memcpy(h.pauliCodes, codes,
                sizeof(enum pauliOpType) * (size_t)h.numSumTerms * h.numQubits);
}

void reportPauliHamil(PauliHamil h) {
    drop(pycall("reportPauliHamil", "(N)", hamil_obj(h)));
}

DiagonalOp createDiagonalOp(int numQubits, QuESTEnv env) {
    drop(pycall("_validate_create_diag", "(ii)", numQubits, env.numRanks));
    DiagonalOp op;
    op.numQubits = numQubits;
    op.numElemsPerChunk = 1LL << numQubits;
    op.numChunks = 1;
    op.chunkId = 0;
    op.real = static_cast<qreal*>(
        std::calloc(op.numElemsPerChunk, sizeof(qreal)));
    op.imag = static_cast<qreal*>(
        std::calloc(op.numElemsPerChunk, sizeof(qreal)));
    op.handle = pycall("createDiagonalOp", "(iN)", numQubits, eh(env));
    return op;
}

void destroyDiagonalOp(DiagonalOp op, QuESTEnv env) {
    (void)env;
    drop(pycall("destroyDiagonalOp", "(N)", dh(op)));
    std::free(op.real);
    std::free(op.imag);
    Py_XDECREF(static_cast<PyObject*>(op.handle));
}

void syncDiagonalOp(DiagonalOp op) {
    // push the host elements to the device copy (ref: agnostic_syncDiagonalOp)
    long long dim = op.numElemsPerChunk;
    drop(pycall("setDiagonalOpElems", "(NLNNL)", dh(op), 0LL,
                double_list(op.real, dim), double_list(op.imag, dim), dim));
}

void initDiagonalOp(DiagonalOp op, qreal* real, qreal* imag) {
    long long dim = op.numElemsPerChunk;
    std::memcpy(op.real, real, sizeof(qreal) * dim);
    std::memcpy(op.imag, imag, sizeof(qreal) * dim);
    syncDiagonalOp(op);
}

void setDiagonalOpElems(DiagonalOp op, long long int startInd,
                        qreal* real, qreal* imag, long long int numElems) {
    // user arrays may be garbage when the indices are invalid (the
    // reference's own validation tests do this) — touch them only after the
    // bounds check; invalid calls still forward so validation raises
    bool ok = startInd >= 0 && numElems >= 0 && real && imag &&
              startInd + numElems <= op.numElemsPerChunk;
    if (ok) {
        std::memcpy(op.real + startInd, real, sizeof(qreal) * numElems);
        std::memcpy(op.imag + startInd, imag, sizeof(qreal) * numElems);
    }
    drop(pycall("setDiagonalOpElems", "(NLNNL)", dh(op), startInd,
                double_list(ok ? real : nullptr, numElems),
                double_list(ok ? imag : nullptr, numElems), numElems));
}

/* ---- state initialisation ---------------------------------------------- */

void initBlankState(Qureg q) { drop(pycall("initBlankState", "(N)", qh(q))); }
void initZeroState(Qureg q) { drop(pycall("initZeroState", "(N)", qh(q))); }
void initPlusState(Qureg q) { drop(pycall("initPlusState", "(N)", qh(q))); }

void initClassicalState(Qureg q, long long int s) {
    drop(pycall("initClassicalState", "(NL)", qh(q), s));
}

void initPureState(Qureg q, Qureg pure) {
    drop(pycall("initPureState", "(NN)", qh(q), qh(pure)));
}

void initDebugState(Qureg q) { drop(pycall("initDebugState", "(N)", qh(q))); }

void initStateFromAmps(Qureg q, qreal* reals, qreal* imags) {
    drop(pycall("initStateFromAmps", "(NNN)", qh(q),
                double_list(reals, q.numAmpsTotal),
                double_list(imags, q.numAmpsTotal)));
}

void setAmps(Qureg q, long long int startInd, qreal* reals, qreal* imags,
             long long int numAmps) {
    bool ok = startInd >= 0 && numAmps >= 0 && reals && imags &&
              startInd + numAmps <= q.numAmpsTotal;
    drop(pycall("setAmps", "(NLNNL)", qh(q), startInd,
                double_list(ok ? reals : nullptr, numAmps),
                double_list(ok ? imags : nullptr, numAmps), numAmps));
}

void setWeightedQureg(Complex fac1, Qureg q1, Complex fac2, Qureg q2,
                      Complex facOut, Qureg out) {
    drop(pycall("setWeightedQureg", "(NNNNNN)", cobj(fac1), qh(q1),
                cobj(fac2), qh(q2), cobj(facOut), qh(out)));
}

/* ---- QASM logging ------------------------------------------------------ */

void startRecordingQASM(Qureg q) { drop(pycall("startRecordingQASM", "(N)", qh(q))); }
void stopRecordingQASM(Qureg q) { drop(pycall("stopRecordingQASM", "(N)", qh(q))); }
void clearRecordedQASM(Qureg q) { drop(pycall("clearRecordedQASM", "(N)", qh(q))); }
void printRecordedQASM(Qureg q) { drop(pycall("printRecordedQASM", "(N)", qh(q))); }

void writeRecordedQASMToFile(Qureg q, char* filename) {
    drop(pycall("writeRecordedQASMToFile", "(Ns)", qh(q), filename));
}

/* ---- unitaries --------------------------------------------------------- */

void phaseShift(Qureg q, int t, qreal a) {
    drop(pycall("phaseShift", "(Nid)", qh(q), t, a));
}

void controlledPhaseShift(Qureg q, int a, int b, qreal angle) {
    drop(pycall("controlledPhaseShift", "(Niid)", qh(q), a, b, angle));
}

void multiControlledPhaseShift(Qureg q, int* qs, int n, qreal angle) {
    drop(pycall("multiControlledPhaseShift", "(NNid)", qh(q), int_list(qs, n),
                n, angle));
}

void controlledPhaseFlip(Qureg q, int a, int b) {
    drop(pycall("controlledPhaseFlip", "(Nii)", qh(q), a, b));
}

void multiControlledPhaseFlip(Qureg q, int* qs, int n) {
    drop(pycall("multiControlledPhaseFlip", "(NNi)", qh(q), int_list(qs, n), n));
}

void sGate(Qureg q, int t) { drop(pycall("sGate", "(Ni)", qh(q), t)); }
void tGate(Qureg q, int t) { drop(pycall("tGate", "(Ni)", qh(q), t)); }

void unitary(Qureg q, int t, ComplexMatrix2 u) {
    drop(pycall("unitary", "(NiN)", qh(q), t, m2(u)));
}

void compactUnitary(Qureg q, int t, Complex alpha, Complex beta) {
    drop(pycall("compactUnitary", "(NiNN)", qh(q), t, cobj(alpha), cobj(beta)));
}

void rotateX(Qureg q, int t, qreal a) { drop(pycall("rotateX", "(Nid)", qh(q), t, a)); }
void rotateY(Qureg q, int t, qreal a) { drop(pycall("rotateY", "(Nid)", qh(q), t, a)); }
void rotateZ(Qureg q, int t, qreal a) { drop(pycall("rotateZ", "(Nid)", qh(q), t, a)); }

void rotateAroundAxis(Qureg q, int t, qreal a, Vector axis) {
    drop(pycall("rotateAroundAxis", "(NidN)", qh(q), t, a, vec_obj(axis)));
}

void controlledRotateX(Qureg q, int c, int t, qreal a) {
    drop(pycall("controlledRotateX", "(Niid)", qh(q), c, t, a));
}

void controlledRotateY(Qureg q, int c, int t, qreal a) {
    drop(pycall("controlledRotateY", "(Niid)", qh(q), c, t, a));
}

void controlledRotateZ(Qureg q, int c, int t, qreal a) {
    drop(pycall("controlledRotateZ", "(Niid)", qh(q), c, t, a));
}

void controlledRotateAroundAxis(Qureg q, int c, int t, qreal a, Vector axis) {
    drop(pycall("controlledRotateAroundAxis", "(NiidN)", qh(q), c, t, a,
                vec_obj(axis)));
}

void controlledCompactUnitary(Qureg q, int c, int t, Complex alpha, Complex beta) {
    drop(pycall("controlledCompactUnitary", "(NiiNN)", qh(q), c, t,
                cobj(alpha), cobj(beta)));
}

void controlledUnitary(Qureg q, int c, int t, ComplexMatrix2 u) {
    drop(pycall("controlledUnitary", "(NiiN)", qh(q), c, t, m2(u)));
}

void multiControlledUnitary(Qureg q, int* cs, int n, int t, ComplexMatrix2 u) {
    drop(pycall("multiControlledUnitary", "(NNiiN)", qh(q), int_list(cs, n), n,
                t, m2(u)));
}

void multiStateControlledUnitary(Qureg q, int* cs, int* states, int n, int t,
                                 ComplexMatrix2 u) {
    drop(pycall("multiStateControlledUnitary", "(NNNiiN)", qh(q),
                int_list(cs, n), int_list(states, n), n, t, m2(u)));
}

void pauliX(Qureg q, int t) { drop(pycall("pauliX", "(Ni)", qh(q), t)); }
void pauliY(Qureg q, int t) { drop(pycall("pauliY", "(Ni)", qh(q), t)); }
void pauliZ(Qureg q, int t) { drop(pycall("pauliZ", "(Ni)", qh(q), t)); }
void hadamard(Qureg q, int t) { drop(pycall("hadamard", "(Ni)", qh(q), t)); }

void controlledNot(Qureg q, int c, int t) {
    drop(pycall("controlledNot", "(Nii)", qh(q), c, t));
}

void controlledPauliY(Qureg q, int c, int t) {
    drop(pycall("controlledPauliY", "(Nii)", qh(q), c, t));
}

void swapGate(Qureg q, int a, int b) {
    drop(pycall("swapGate", "(Nii)", qh(q), a, b));
}

void sqrtSwapGate(Qureg q, int a, int b) {
    drop(pycall("sqrtSwapGate", "(Nii)", qh(q), a, b));
}

void multiRotateZ(Qureg q, int* qs, int n, qreal angle) {
    drop(pycall("multiRotateZ", "(NNid)", qh(q), int_list(qs, n), n, angle));
}

void multiRotatePauli(Qureg q, int* ts, enum pauliOpType* paulis, int n,
                      qreal angle) {
    drop(pycall("multiRotatePauli", "(NNNid)", qh(q), int_list(ts, n),
                pauli_list(paulis, n), n, angle));
}

void twoQubitUnitary(Qureg q, int t1, int t2, ComplexMatrix4 u) {
    if (!fits_ok(q, 2, "twoQubitUnitary")) return;
    drop(pycall("twoQubitUnitary", "(NiiN)", qh(q), t1, t2, m4(u)));
}

void controlledTwoQubitUnitary(Qureg q, int c, int t1, int t2, ComplexMatrix4 u) {
    if (!fits_ok(q, 2, "controlledTwoQubitUnitary")) return;
    drop(pycall("controlledTwoQubitUnitary", "(NiiiN)", qh(q), c, t1, t2, m4(u)));
}

void multiControlledTwoQubitUnitary(Qureg q, int* cs, int n, int t1, int t2,
                                    ComplexMatrix4 u) {
    if (!fits_ok(q, 2, "multiControlledTwoQubitUnitary")) return;
    drop(pycall("multiControlledTwoQubitUnitary", "(NNiiiN)", qh(q),
                int_list(cs, n), n, t1, t2, m4(u)));
}

void multiQubitUnitary(Qureg q, int* ts, int n, ComplexMatrixN u) {
    if (!matrixN_ok(u, "multiQubitUnitary")) return;
    if (!fits_ok(q, n, "multiQubitUnitary")) return;
    drop(pycall("multiQubitUnitary", "(NNiN)", qh(q), int_list(ts, n), n, mN(u)));
}

void controlledMultiQubitUnitary(Qureg q, int c, int* ts, int n, ComplexMatrixN u) {
    if (!matrixN_ok(u, "controlledMultiQubitUnitary")) return;
    if (!fits_ok(q, n, "controlledMultiQubitUnitary")) return;
    drop(pycall("controlledMultiQubitUnitary", "(NiNiN)", qh(q), c,
                int_list(ts, n), n, mN(u)));
}

void multiControlledMultiQubitUnitary(Qureg q, int* cs, int nc, int* ts, int nt,
                                      ComplexMatrixN u) {
    if (!matrixN_ok(u, "multiControlledMultiQubitUnitary")) return;
    if (!fits_ok(q, nt, "multiControlledMultiQubitUnitary")) return;
    drop(pycall("multiControlledMultiQubitUnitary", "(NNiNiN)", qh(q),
                int_list(cs, nc), nc, int_list(ts, nt), nt, mN(u)));
}

/* ---- operators --------------------------------------------------------- */

void applyMatrix2(Qureg q, int t, ComplexMatrix2 u) {
    drop(pycall("applyMatrix2", "(NiN)", qh(q), t, m2(u)));
}

void applyMatrix4(Qureg q, int t1, int t2, ComplexMatrix4 u) {
    if (!fits_ok(q, 2, "applyMatrix4")) return;
    drop(pycall("applyMatrix4", "(NiiN)", qh(q), t1, t2, m4(u)));
}

void applyMatrixN(Qureg q, int* ts, int n, ComplexMatrixN u) {
    if (!matrixN_ok(u, "applyMatrixN")) return;
    if (!fits_ok(q, n, "applyMatrixN")) return;
    drop(pycall("applyMatrixN", "(NNiN)", qh(q), int_list(ts, n), n, mN(u)));
}

void applyMultiControlledMatrixN(Qureg q, int* cs, int nc, int* ts, int nt,
                                 ComplexMatrixN u) {
    if (!matrixN_ok(u, "applyMultiControlledMatrixN")) return;
    if (!fits_ok(q, nt, "applyMultiControlledMatrixN")) return;
    drop(pycall("applyMultiControlledMatrixN", "(NNiNiN)", qh(q),
                int_list(cs, nc), nc, int_list(ts, nt), nt, mN(u)));
}

void applyPauliSum(Qureg inQureg, enum pauliOpType* codes, qreal* coeffs,
                   int numSumTerms, Qureg outQureg) {
    drop(pycall("applyPauliSum", "(NNNiN)", qh(inQureg),
                pauli_list(codes,
                           (long long)numSumTerms * inQureg.numQubitsRepresented),
                double_list(coeffs, numSumTerms), numSumTerms, qh(outQureg)));
}

void applyPauliHamil(Qureg inQureg, PauliHamil hamil, Qureg outQureg) {
    drop(pycall("applyPauliHamil", "(NNN)", qh(inQureg), hamil_obj(hamil),
                qh(outQureg)));
}

void applyTrotterCircuit(Qureg q, PauliHamil hamil, qreal time, int order,
                         int reps) {
    drop(pycall("applyTrotterCircuit", "(NNdii)", qh(q), hamil_obj(hamil),
                time, order, reps));
}

void applyDiagonalOp(Qureg q, DiagonalOp op) {
    drop(pycall("applyDiagonalOp", "(NN)", qh(q), dh(op)));
}

/* ---- decoherence ------------------------------------------------------- */

void mixDephasing(Qureg q, int t, qreal p) {
    drop(pycall("mixDephasing", "(Nid)", qh(q), t, p));
}

void mixTwoQubitDephasing(Qureg q, int a, int b, qreal p) {
    drop(pycall("mixTwoQubitDephasing", "(Niid)", qh(q), a, b, p));
}

void mixDepolarising(Qureg q, int t, qreal p) {
    drop(pycall("mixDepolarising", "(Nid)", qh(q), t, p));
}

void mixTwoQubitDepolarising(Qureg q, int a, int b, qreal p) {
    drop(pycall("mixTwoQubitDepolarising", "(Niid)", qh(q), a, b, p));
}

void mixDamping(Qureg q, int t, qreal p) {
    drop(pycall("mixDamping", "(Nid)", qh(q), t, p));
}

void mixPauli(Qureg q, int t, qreal px, qreal py, qreal pz) {
    drop(pycall("mixPauli", "(Niddd)", qh(q), t, px, py, pz));
}

void mixDensityMatrix(Qureg combineQureg, qreal prob, Qureg otherQureg) {
    drop(pycall("mixDensityMatrix", "(NdN)", qh(combineQureg), prob,
                qh(otherQureg)));
}

void mixKrausMap(Qureg q, int t, ComplexMatrix2* ops, int numOps) {
    if (!fits_ok(q, 2, "mixKrausMap")) return;
    drop(pycall("mixKrausMap", "(NiNi)", qh(q), t, m2_list(ops, numOps), numOps));
}

void mixTwoQubitKrausMap(Qureg q, int t1, int t2, ComplexMatrix4* ops, int numOps) {
    if (!fits_ok(q, 4, "mixTwoQubitKrausMap")) return;
    drop(pycall("mixTwoQubitKrausMap", "(NiiNi)", qh(q), t1, t2,
                m4_list(ops, numOps), numOps));
}

void mixMultiQubitKrausMap(Qureg q, int* ts, int numTargets,
                           ComplexMatrixN* ops, int numOps) {
    if (!fits_ok(q, 2 * numTargets, "mixMultiQubitKrausMap")) return;
    // every operator must be a created matrix BEFORE any is converted: the
    // reference's validation tests pass arrays where one op has NULL arrays
    // and the rest hold uninitialized garbage pointers
    if (ops)
        for (int i = 0; i < numOps; i++)
            if (!matrixN_ok(ops[i], "mixMultiQubitKrausMap")) return;
    drop(pycall("mixMultiQubitKrausMap", "(NNiNi)", qh(q),
                int_list(ts, numTargets), numTargets, mN_list(ops, numOps),
                numOps));
}

/* ---- measurement & calculations ---------------------------------------- */

int measure(Qureg q, int t) {
    return static_cast<int>(to_ll(pycall("measure", "(Ni)", qh(q), t)));
}

int measureWithStats(Qureg q, int t, qreal* outcomeProb) {
    PyObject* pair = pycall("measureWithStats", "(Ni)", qh(q), t);
    int outcome = 0;
    *outcomeProb = 0.0;
    if (pair && PyTuple_Check(pair) && PyTuple_Size(pair) == 2) {
        outcome = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(pair, 0)));
        *outcomeProb = PyFloat_AsDouble(PyTuple_GetItem(pair, 1));
        PyErr_Clear();
    }
    drop(pair);
    return outcome;
}

qreal collapseToOutcome(Qureg q, int t, int outcome) {
    return to_double(pycall("collapseToOutcome", "(Nii)", qh(q), t, outcome));
}

qreal calcProbOfOutcome(Qureg q, int t, int outcome) {
    return to_double(pycall("calcProbOfOutcome", "(Nii)", qh(q), t, outcome));
}

qreal calcTotalProb(Qureg q) {
    return to_double(pycall("calcTotalProb", "(N)", qh(q)));
}

Complex getAmp(Qureg q, long long int i) {
    return to_cmplx(pycall("getAmp", "(NL)", qh(q), i));
}

qreal getRealAmp(Qureg q, long long int i) {
    return to_double(pycall("getRealAmp", "(NL)", qh(q), i));
}

qreal getImagAmp(Qureg q, long long int i) {
    return to_double(pycall("getImagAmp", "(NL)", qh(q), i));
}

qreal getProbAmp(Qureg q, long long int i) {
    return to_double(pycall("getProbAmp", "(NL)", qh(q), i));
}

Complex getDensityAmp(Qureg q, long long int row, long long int col) {
    return to_cmplx(pycall("getDensityAmp", "(NLL)", qh(q), row, col));
}

Complex calcInnerProduct(Qureg bra, Qureg ket) {
    return to_cmplx(pycall("calcInnerProduct", "(NN)", qh(bra), qh(ket)));
}

qreal calcDensityInnerProduct(Qureg rho1, Qureg rho2) {
    return to_double(pycall("calcDensityInnerProduct", "(NN)", qh(rho1), qh(rho2)));
}

qreal calcPurity(Qureg q) { return to_double(pycall("calcPurity", "(N)", qh(q))); }

qreal calcFidelity(Qureg q, Qureg pureState) {
    return to_double(pycall("calcFidelity", "(NN)", qh(q), qh(pureState)));
}

qreal calcHilbertSchmidtDistance(Qureg a, Qureg b) {
    return to_double(pycall("calcHilbertSchmidtDistance", "(NN)", qh(a), qh(b)));
}

qreal calcExpecPauliProd(Qureg q, int* ts, enum pauliOpType* codes,
                         int numTargets, Qureg workspace) {
    return to_double(pycall("calcExpecPauliProd", "(NNNiN)", qh(q),
                            int_list(ts, numTargets),
                            pauli_list(codes, numTargets), numTargets,
                            qh(workspace)));
}

qreal calcExpecPauliSum(Qureg q, enum pauliOpType* codes, qreal* coeffs,
                        int numSumTerms, Qureg workspace) {
    return to_double(pycall("calcExpecPauliSum", "(NNNiN)", qh(q),
                            pauli_list(codes, (long long)numSumTerms *
                                       q.numQubitsRepresented),
                            double_list(coeffs, numSumTerms), numSumTerms,
                            qh(workspace)));
}

qreal calcExpecPauliHamil(Qureg q, PauliHamil hamil, Qureg workspace) {
    return to_double(pycall("calcExpecPauliHamil", "(NNN)", qh(q),
                            hamil_obj(hamil), qh(workspace)));
}

Complex calcExpecDiagonalOp(Qureg q, DiagonalOp op) {
    return to_cmplx(pycall("calcExpecDiagonalOp", "(NN)", qh(q), dh(op)));
}

/* ---- debug API --------------------------------------------------------- */

void initStateDebug(Qureg q) { drop(pycall("initStateDebug", "(N)", qh(q))); }

void initStateOfSingleQubit(Qureg* q, int qubitId, int outcome) {
    drop(pycall("initStateOfSingleQubit", "(Nii)", qh(*q), qubitId, outcome));
}

int initStateFromSingleFile(Qureg* q, char filename[200], QuESTEnv env) {
    (void)env;
    return static_cast<int>(to_ll(
        pycall("initStateFromSingleFile", "(Ns)", qh(*q), filename)));
}

void setDensityAmps(Qureg q, qreal* reals, qreal* imags) {
    drop(pycall("setDensityAmps", "(NNN)", qh(q),
                double_list(reals, q.numAmpsTotal),
                double_list(imags, q.numAmpsTotal)));
}

int compareStates(Qureg a, Qureg b, qreal precision) {
    PyObject* r = pycall("compareStates", "(NNd)", qh(a), qh(b), precision);
    int ok = r ? (PyObject_IsTrue(r) == 1) : 0;
    drop(r);
    return ok;
}

int QuESTPrecision(void) {
    return static_cast<int>(to_ll(pycall("QuESTPrecision", nullptr)));
}

}  // extern "C"
