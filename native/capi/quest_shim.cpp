// C front-end implementation: bridges the QuEST-compatible C API
// (quest_tpu_c.h) onto the quest_tpu Python/JAX runtime via an embedded
// CPython interpreter.
//
// Architecture: the reference links user C programs against native kernels
// directly (libQuEST.so); here the "kernels" are XLA programs managed by the
// Python runtime, so the shim owns an interpreter, imports quest_tpu once,
// and forwards each C call.  Handles in the public structs are PyObject
// pointers.  Every call clears/raises on Python errors by printing and
// exiting, matching the reference's exit-on-invalid-input behaviour
// (ref: QuEST_validation.c exitWithError:167-173).

#include "quest_tpu_c.h"

#include <Python.h>

#include <cstdio>
#include <cstdlib>

namespace {

PyObject* g_module = nullptr;

void die_on_python_error() {
    if (PyErr_Occurred()) {
        PyErr_Print();
        std::exit(1);
    }
}

PyObject* mod() {
    if (!g_module) {
        if (!Py_IsInitialized()) {
            Py_Initialize();
        }
        g_module = PyImport_ImportModule("quest_tpu");
        die_on_python_error();
    }
    return g_module;
}

// call quest_tpu.<name>(args...) with a new reference result.  stdout is
// flushed on both sides so C printf and Python print interleave in order.
PyObject* call(const char* name, PyObject* args) {
    std::fflush(stdout);
    PyObject* fn = PyObject_GetAttrString(mod(), name);
    die_on_python_error();
    PyObject* result = PyObject_CallObject(fn, args);
    Py_XDECREF(fn);
    Py_XDECREF(args);
    die_on_python_error();
    PyRun_SimpleString("import sys; sys.stdout.flush()");
    return result;
}

PyObject* int_list(const int* xs, int n) {
    PyObject* list = PyList_New(n);
    for (int i = 0; i < n; i++)
        PyList_SET_ITEM(list, i, PyLong_FromLong(xs[i]));
    return list;
}

PyObject* complex_obj(Complex c) {
    return PyComplex_FromDoubles(c.real, c.imag);
}

PyObject* matrix2_obj(ComplexMatrix2 u) {
    PyObject* rows = PyList_New(2);
    for (int r = 0; r < 2; r++) {
        PyObject* row = PyList_New(2);
        for (int c = 0; c < 2; c++)
            PyList_SET_ITEM(row, c, PyComplex_FromDoubles(u.real[r][c],
                                                          u.imag[r][c]));
        PyList_SET_ITEM(rows, r, row);
    }
    return rows;
}

PyObject* matrixN_obj(ComplexMatrixN u) {
    int dim = 1 << u.numQubits;
    PyObject* rows = PyList_New(dim);
    for (int r = 0; r < dim; r++) {
        PyObject* row = PyList_New(dim);
        for (int c = 0; c < dim; c++)
            PyList_SET_ITEM(row, c, PyComplex_FromDoubles(u.real[r][c],
                                                          u.imag[r][c]));
        PyList_SET_ITEM(rows, r, row);
    }
    return rows;
}

double as_double(PyObject* o) {
    double v = PyFloat_AsDouble(o);
    die_on_python_error();
    Py_XDECREF(o);
    return v;
}

long as_long(PyObject* o) {
    long v = PyLong_AsLong(o);
    die_on_python_error();
    Py_XDECREF(o);
    return v;
}

PyObject* qureg_handle(Qureg q) {
    PyObject* h = static_cast<PyObject*>(q.handle);
    Py_INCREF(h);
    return h;
}

// gate helper: quest_tpu.<name>(qureg, ...) discarding the result
void gate_call(const char* name, Qureg q, PyObject* rest /* tuple or null */) {
    Py_ssize_t extra = rest ? PyTuple_Size(rest) : 0;
    PyObject* args = PyTuple_New(1 + extra);
    PyTuple_SET_ITEM(args, 0, qureg_handle(q));
    for (Py_ssize_t i = 0; i < extra; i++) {
        PyObject* item = PyTuple_GetItem(rest, i);
        Py_INCREF(item);
        PyTuple_SET_ITEM(args, 1 + i, item);
    }
    Py_XDECREF(rest);
    Py_XDECREF(call(name, args));
}

}  // namespace

extern "C" {

QuESTEnv createQuESTEnv(void) {
    PyObject* env = call("createQuESTEnv", nullptr);
    QuESTEnv out;
    out.rank = 0;
    PyObject* nr = PyObject_GetAttrString(env, "num_ranks");
    out.numRanks = static_cast<int>(PyLong_AsLong(nr));
    Py_XDECREF(nr);
    out.handle = env;
    return out;
}

void destroyQuESTEnv(QuESTEnv env) {
    Py_XDECREF(static_cast<PyObject*>(env.handle));
}

void syncQuESTEnv(QuESTEnv env) {
    PyObject* args = PyTuple_New(1);
    PyObject* h = static_cast<PyObject*>(env.handle);
    Py_INCREF(h);
    PyTuple_SET_ITEM(args, 0, h);
    Py_XDECREF(call("syncQuESTEnv", args));
}

void reportQuESTEnv(QuESTEnv env) {
    PyObject* args = PyTuple_New(1);
    PyObject* h = static_cast<PyObject*>(env.handle);
    Py_INCREF(h);
    PyTuple_SET_ITEM(args, 0, h);
    Py_XDECREF(call("reportQuESTEnv", args));
}

void seedQuEST(unsigned long int* seedArray, int numSeeds) {
    PyObject* list = PyList_New(numSeeds);
    for (int i = 0; i < numSeeds; i++)
        PyList_SET_ITEM(list, i, PyLong_FromUnsignedLong(seedArray[i]));
    PyObject* args = PyTuple_Pack(2, list, PyLong_FromLong(numSeeds));
    Py_XDECREF(call("seedQuEST", args));
}

static Qureg make_qureg(const char* ctor, int numQubits, QuESTEnv env) {
    PyObject* h = static_cast<PyObject*>(env.handle);
    Py_INCREF(h);
    PyObject* args = PyTuple_New(2);
    PyTuple_SET_ITEM(args, 0, PyLong_FromLong(numQubits));
    PyTuple_SET_ITEM(args, 1, h);
    PyObject* q = call(ctor, args);
    Qureg out;
    PyObject* isdm = PyObject_GetAttrString(q, "is_density_matrix");
    out.isDensityMatrix = PyObject_IsTrue(isdm);
    Py_XDECREF(isdm);
    out.numQubitsRepresented = numQubits;
    out.numAmpsTotal = 1LL << (numQubits * (out.isDensityMatrix ? 2 : 1));
    out.handle = q;
    return out;
}

Qureg createQureg(int numQubits, QuESTEnv env) {
    return make_qureg("createQureg", numQubits, env);
}

Qureg createDensityQureg(int numQubits, QuESTEnv env) {
    return make_qureg("createDensityQureg", numQubits, env);
}

void destroyQureg(Qureg qureg, QuESTEnv env) {
    (void)env;
    gate_call("destroyQureg", qureg, nullptr);
    Py_XDECREF(static_cast<PyObject*>(qureg.handle));
}

void reportQuregParams(Qureg qureg) { gate_call("reportQuregParams", qureg, nullptr); }

void reportStateToScreen(Qureg qureg, QuESTEnv env, int reportRank) {
    PyObject* h = static_cast<PyObject*>(env.handle);
    Py_INCREF(h);
    gate_call("reportStateToScreen", qureg,
              PyTuple_Pack(2, h, PyLong_FromLong(reportRank)));
}

ComplexMatrixN createComplexMatrixN(int numQubits) {
    int dim = 1 << numQubits;
    ComplexMatrixN m;
    m.numQubits = numQubits;
    m.real = static_cast<qreal**>(std::calloc(dim, sizeof(qreal*)));
    m.imag = static_cast<qreal**>(std::calloc(dim, sizeof(qreal*)));
    for (int r = 0; r < dim; r++) {
        m.real[r] = static_cast<qreal*>(std::calloc(dim, sizeof(qreal)));
        m.imag[r] = static_cast<qreal*>(std::calloc(dim, sizeof(qreal)));
    }
    return m;
}

void destroyComplexMatrixN(ComplexMatrixN m) {
    int dim = 1 << m.numQubits;
    for (int r = 0; r < dim; r++) {
        std::free(m.real[r]);
        std::free(m.imag[r]);
    }
    std::free(m.real);
    std::free(m.imag);
}

/* state initialisation */
void initZeroState(Qureg q) { gate_call("initZeroState", q, nullptr); }
void initPlusState(Qureg q) { gate_call("initPlusState", q, nullptr); }
void initBlankState(Qureg q) { gate_call("initBlankState", q, nullptr); }
void initClassicalState(Qureg q, long long int s) {
    gate_call("initClassicalState", q, PyTuple_Pack(1, PyLong_FromLongLong(s)));
}

/* gates */
void hadamard(Qureg q, int t) { gate_call("hadamard", q, PyTuple_Pack(1, PyLong_FromLong(t))); }
void pauliX(Qureg q, int t) { gate_call("pauliX", q, PyTuple_Pack(1, PyLong_FromLong(t))); }
void pauliY(Qureg q, int t) { gate_call("pauliY", q, PyTuple_Pack(1, PyLong_FromLong(t))); }
void pauliZ(Qureg q, int t) { gate_call("pauliZ", q, PyTuple_Pack(1, PyLong_FromLong(t))); }
void sGate(Qureg q, int t) { gate_call("sGate", q, PyTuple_Pack(1, PyLong_FromLong(t))); }
void tGate(Qureg q, int t) { gate_call("tGate", q, PyTuple_Pack(1, PyLong_FromLong(t))); }

void phaseShift(Qureg q, int t, qreal a) {
    gate_call("phaseShift", q, PyTuple_Pack(2, PyLong_FromLong(t), PyFloat_FromDouble(a)));
}
void rotateX(Qureg q, int t, qreal a) {
    gate_call("rotateX", q, PyTuple_Pack(2, PyLong_FromLong(t), PyFloat_FromDouble(a)));
}
void rotateY(Qureg q, int t, qreal a) {
    gate_call("rotateY", q, PyTuple_Pack(2, PyLong_FromLong(t), PyFloat_FromDouble(a)));
}
void rotateZ(Qureg q, int t, qreal a) {
    gate_call("rotateZ", q, PyTuple_Pack(2, PyLong_FromLong(t), PyFloat_FromDouble(a)));
}

void rotateAroundAxis(Qureg q, int t, qreal a, Vector axis) {
    PyObject* ax = PyTuple_Pack(3, PyFloat_FromDouble(axis.x),
                                PyFloat_FromDouble(axis.y),
                                PyFloat_FromDouble(axis.z));
    gate_call("rotateAroundAxis", q,
              PyTuple_Pack(3, PyLong_FromLong(t), PyFloat_FromDouble(a), ax));
}

void controlledNot(Qureg q, int c, int t) {
    gate_call("controlledNot", q, PyTuple_Pack(2, PyLong_FromLong(c), PyLong_FromLong(t)));
}
void controlledPhaseFlip(Qureg q, int a, int b) {
    gate_call("controlledPhaseFlip", q, PyTuple_Pack(2, PyLong_FromLong(a), PyLong_FromLong(b)));
}
void controlledPhaseShift(Qureg q, int a, int b, qreal angle) {
    gate_call("controlledPhaseShift", q,
              PyTuple_Pack(3, PyLong_FromLong(a), PyLong_FromLong(b),
                           PyFloat_FromDouble(angle)));
}
void multiControlledPhaseFlip(Qureg q, int* qs, int n) {
    gate_call("multiControlledPhaseFlip", q,
              PyTuple_Pack(2, int_list(qs, n), PyLong_FromLong(n)));
}
void swapGate(Qureg q, int a, int b) {
    gate_call("swapGate", q, PyTuple_Pack(2, PyLong_FromLong(a), PyLong_FromLong(b)));
}

void unitary(Qureg q, int t, ComplexMatrix2 u) {
    gate_call("unitary", q, PyTuple_Pack(2, PyLong_FromLong(t), matrix2_obj(u)));
}
void compactUnitary(Qureg q, int t, Complex alpha, Complex beta) {
    gate_call("compactUnitary", q,
              PyTuple_Pack(3, PyLong_FromLong(t), complex_obj(alpha), complex_obj(beta)));
}
void controlledCompactUnitary(Qureg q, int c, int t, Complex alpha, Complex beta) {
    gate_call("controlledCompactUnitary", q,
              PyTuple_Pack(4, PyLong_FromLong(c), PyLong_FromLong(t),
                           complex_obj(alpha), complex_obj(beta)));
}
void controlledUnitary(Qureg q, int c, int t, ComplexMatrix2 u) {
    gate_call("controlledUnitary", q,
              PyTuple_Pack(3, PyLong_FromLong(c), PyLong_FromLong(t), matrix2_obj(u)));
}
void multiControlledUnitary(Qureg q, int* cs, int n, int t, ComplexMatrix2 u) {
    gate_call("multiControlledUnitary", q,
              PyTuple_Pack(4, int_list(cs, n), PyLong_FromLong(n),
                           PyLong_FromLong(t), matrix2_obj(u)));
}
void multiQubitUnitary(Qureg q, int* ts, int n, ComplexMatrixN u) {
    gate_call("multiQubitUnitary", q,
              PyTuple_Pack(3, int_list(ts, n), PyLong_FromLong(n), matrixN_obj(u)));
}

/* measurement & calculations */
static PyObject* q1(Qureg q, long long x) {
    PyObject* args = PyTuple_New(2);
    PyTuple_SET_ITEM(args, 0, qureg_handle(q));
    PyTuple_SET_ITEM(args, 1, PyLong_FromLongLong(x));
    return args;
}

int measure(Qureg q, int t) { return static_cast<int>(as_long(call("measure", q1(q, t)))); }

int measureWithStats(Qureg q, int t, qreal* outcomeProb) {
    PyObject* pair = call("measureWithStats", q1(q, t));
    int outcome = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(pair, 0)));
    *outcomeProb = PyFloat_AsDouble(PyTuple_GetItem(pair, 1));
    die_on_python_error();
    Py_XDECREF(pair);
    return outcome;
}

qreal collapseToOutcome(Qureg q, int t, int outcome) {
    PyObject* args = PyTuple_New(3);
    PyTuple_SET_ITEM(args, 0, qureg_handle(q));
    PyTuple_SET_ITEM(args, 1, PyLong_FromLong(t));
    PyTuple_SET_ITEM(args, 2, PyLong_FromLong(outcome));
    return as_double(call("collapseToOutcome", args));
}

qreal calcProbOfOutcome(Qureg q, int t, int outcome) {
    PyObject* args = PyTuple_New(3);
    PyTuple_SET_ITEM(args, 0, qureg_handle(q));
    PyTuple_SET_ITEM(args, 1, PyLong_FromLong(t));
    PyTuple_SET_ITEM(args, 2, PyLong_FromLong(outcome));
    return as_double(call("calcProbOfOutcome", args));
}

qreal calcTotalProb(Qureg q) {
    PyObject* args = PyTuple_New(1);
    PyTuple_SET_ITEM(args, 0, qureg_handle(q));
    return as_double(call("calcTotalProb", args));
}

qreal getProbAmp(Qureg q, long long int i) { return as_double(call("getProbAmp", q1(q, i))); }
qreal getRealAmp(Qureg q, long long int i) { return as_double(call("getRealAmp", q1(q, i))); }
qreal getImagAmp(Qureg q, long long int i) { return as_double(call("getImagAmp", q1(q, i))); }

/* decoherence */
void mixDamping(Qureg q, int t, qreal p) {
    gate_call("mixDamping", q, PyTuple_Pack(2, PyLong_FromLong(t), PyFloat_FromDouble(p)));
}
void mixDephasing(Qureg q, int t, qreal p) {
    gate_call("mixDephasing", q, PyTuple_Pack(2, PyLong_FromLong(t), PyFloat_FromDouble(p)));
}
void mixDepolarising(Qureg q, int t, qreal p) {
    gate_call("mixDepolarising", q, PyTuple_Pack(2, PyLong_FromLong(t), PyFloat_FromDouble(p)));
}

}  // extern "C"
