/* Compatibility alias: lets programs written against the reference's
 * `#include "QuEST.h"` compile against the quest_tpu C front-end unchanged. */
#include "quest_tpu_c.h"
