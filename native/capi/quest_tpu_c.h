/* C front-end for the quest_tpu TPU-native simulation framework.
 *
 * Declares a QuEST-compatible C API (same function names, argument orders
 * and value-struct conventions as QuEST.h v3.2 — independently written) so
 * existing C driver programs compile against this framework unchanged and
 * execute on the JAX/XLA runtime via an embedded Python interpreter.
 *
 * Link: -lquest_tpu_c (built by native/capi/build.sh).
 */

#ifndef QUEST_TPU_C_H
#define QUEST_TPU_C_H

#ifdef __cplusplus
extern "C" {
#endif

typedef double qreal;

typedef struct Complex {
    qreal real;
    qreal imag;
} Complex;

typedef struct ComplexMatrix2 {
    qreal real[2][2];
    qreal imag[2][2];
} ComplexMatrix2;

typedef struct ComplexMatrix4 {
    qreal real[4][4];
    qreal imag[4][4];
} ComplexMatrix4;

typedef struct ComplexMatrixN {
    int numQubits;
    qreal **real;
    qreal **imag;
} ComplexMatrixN;

typedef struct Vector {
    qreal x, y, z;
} Vector;

enum pauliOpType {PAULI_I = 0, PAULI_X = 1, PAULI_Y = 2, PAULI_Z = 3};

typedef struct QuESTEnv {
    int rank;
    int numRanks;
    void *handle;
} QuESTEnv;

typedef struct Qureg {
    int isDensityMatrix;
    int numQubitsRepresented;
    long long int numAmpsTotal;
    void *handle;
} Qureg;

/* environment */
QuESTEnv createQuESTEnv(void);
void destroyQuESTEnv(QuESTEnv env);
void syncQuESTEnv(QuESTEnv env);
void reportQuESTEnv(QuESTEnv env);
void seedQuEST(unsigned long int *seedArray, int numSeeds);

/* registers */
Qureg createQureg(int numQubits, QuESTEnv env);
Qureg createDensityQureg(int numQubits, QuESTEnv env);
void destroyQureg(Qureg qureg, QuESTEnv env);
void reportQuregParams(Qureg qureg);
void reportStateToScreen(Qureg qureg, QuESTEnv env, int reportRank);

/* matrices */
ComplexMatrixN createComplexMatrixN(int numQubits);
void destroyComplexMatrixN(ComplexMatrixN matr);

/* state initialisation */
void initZeroState(Qureg qureg);
void initPlusState(Qureg qureg);
void initClassicalState(Qureg qureg, long long int stateInd);
void initBlankState(Qureg qureg);

/* gates */
void hadamard(Qureg qureg, int targetQubit);
void pauliX(Qureg qureg, int targetQubit);
void pauliY(Qureg qureg, int targetQubit);
void pauliZ(Qureg qureg, int targetQubit);
void sGate(Qureg qureg, int targetQubit);
void tGate(Qureg qureg, int targetQubit);
void phaseShift(Qureg qureg, int targetQubit, qreal angle);
void rotateX(Qureg qureg, int rotQubit, qreal angle);
void rotateY(Qureg qureg, int rotQubit, qreal angle);
void rotateZ(Qureg qureg, int rotQubit, qreal angle);
void rotateAroundAxis(Qureg qureg, int rotQubit, qreal angle, Vector axis);
void controlledNot(Qureg qureg, int controlQubit, int targetQubit);
void controlledPhaseFlip(Qureg qureg, int idQubit1, int idQubit2);
void controlledPhaseShift(Qureg qureg, int idQubit1, int idQubit2, qreal angle);
void multiControlledPhaseFlip(Qureg qureg, int *controlQubits, int numControlQubits);
void swapGate(Qureg qureg, int qubit1, int qubit2);
void unitary(Qureg qureg, int targetQubit, ComplexMatrix2 u);
void compactUnitary(Qureg qureg, int targetQubit, Complex alpha, Complex beta);
void controlledCompactUnitary(Qureg qureg, int controlQubit, int targetQubit,
                              Complex alpha, Complex beta);
void controlledUnitary(Qureg qureg, int controlQubit, int targetQubit,
                       ComplexMatrix2 u);
void multiControlledUnitary(Qureg qureg, int *controlQubits,
                            int numControlQubits, int targetQubit,
                            ComplexMatrix2 u);
void multiQubitUnitary(Qureg qureg, int *targs, int numTargs, ComplexMatrixN u);

/* measurement & calculations */
int measure(Qureg qureg, int measureQubit);
int measureWithStats(Qureg qureg, int measureQubit, qreal *outcomeProb);
qreal collapseToOutcome(Qureg qureg, int measureQubit, int outcome);
qreal calcProbOfOutcome(Qureg qureg, int measureQubit, int outcome);
qreal calcTotalProb(Qureg qureg);
qreal getProbAmp(Qureg qureg, long long int index);
qreal getRealAmp(Qureg qureg, long long int index);
qreal getImagAmp(Qureg qureg, long long int index);

/* decoherence */
void mixDamping(Qureg qureg, int targetQubit, qreal prob);
void mixDephasing(Qureg qureg, int targetQubit, qreal prob);
void mixDepolarising(Qureg qureg, int targetQubit, qreal prob);

#ifdef __cplusplus
}
#endif

#endif /* QUEST_TPU_C_H */
