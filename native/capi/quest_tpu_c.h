/* C front-end for the quest_tpu TPU-native simulation framework.
 *
 * Declares the full QuEST-compatible C API (same function names, argument
 * orders and value-struct conventions as QuEST.h v3.2 — independently
 * written against that interface contract) so existing C driver programs,
 * including the reference's own examples, compile against this framework
 * unchanged and execute on the JAX/XLA runtime via an embedded Python
 * interpreter.
 *
 * Link: -lquest_tpu_c (built by native/capi/build.sh).
 */

#ifndef QUEST_TPU_C_H
#define QUEST_TPU_C_H

#ifdef __cplusplus
extern "C" {
#endif

/* precision: the C boundary is always double (QuEST precision 2); the
 * runtime may compute in f32 or f64 (QUEST_TPU_PRECISION). */
#define QuEST_PREC 2
typedef double qreal;
#define REAL_EPS 1e-13
#define REAL_SPECIFIER "%lf"
#define REAL_STRING_FORMAT "%.14f"
#define REAL_QASM_FORMAT "%.14g"
#define MPI_MAX_AMPS_IN_MSG (1LL<<28)
#define absReal(X) fabs(X)

typedef struct Complex {
    qreal real;
    qreal imag;
} Complex;

/* struct-of-arrays amplitude mirror (ref layout: QuEST.h:77-81) */
typedef struct ComplexArray {
    qreal *real;
    qreal *imag;
} ComplexArray;

typedef struct ComplexMatrix2 {
    qreal real[2][2];
    qreal imag[2][2];
} ComplexMatrix2;

typedef struct ComplexMatrix4 {
    qreal real[4][4];
    qreal imag[4][4];
} ComplexMatrix4;

typedef struct ComplexMatrixN {
    int numQubits;
    qreal **real;
    qreal **imag;
} ComplexMatrixN;

typedef struct Vector {
    qreal x, y, z;
} Vector;

enum pauliOpType {PAULI_I = 0, PAULI_X = 1, PAULI_Y = 2, PAULI_Z = 3};
enum phaseGateType {SIGMA_Z = 0, S_GATE = 1, T_GATE = 2};

typedef struct PauliHamil {
    enum pauliOpType *pauliCodes; /* numSumTerms * numQubits, term-major */
    qreal *termCoeffs;
    int numSumTerms;
    int numQubits;
} PauliHamil;

typedef struct QuESTEnv {
    int rank;
    int numRanks;
    void *handle;                 /* Python QuESTEnv */
} QuESTEnv;

typedef struct Qureg {
    int isDensityMatrix;
    int numQubitsRepresented;
    int numQubitsInStateVec;
    long long int numAmpsPerChunk;
    long long int numAmpsTotal;
    int chunkId;
    int numChunks;
    ComplexArray stateVec;        /* host mirror, filled by copyStateFromGPU */
    ComplexArray pairStateVec;    /* unused (no MPI pair buffer on TPU) */
    void *handle;                 /* Python Qureg */
} Qureg;

typedef struct DiagonalOp {
    int numQubits;
    long long int numElemsPerChunk;
    int numChunks;
    int chunkId;
    qreal *real;                  /* host elements; push with syncDiagonalOp */
    qreal *imag;
    void *handle;                 /* Python DiagonalOp */
} DiagonalOp;

/* error hook: default prints and exits (ref: QuEST_validation.c:167-178);
 * override (e.g. to throw a C++ exception in tests) by defining a non-weak
 * symbol of the same name. */
void invalidQuESTInputError(const char* errMsg, const char* errFunc);

/* environment */
QuESTEnv createQuESTEnv(void);
void destroyQuESTEnv(QuESTEnv env);
void syncQuESTEnv(QuESTEnv env);
int syncQuESTSuccess(int successCode);
void reportQuESTEnv(QuESTEnv env);
void getEnvironmentString(QuESTEnv env, Qureg qureg, char str[200]);
void seedQuEST(unsigned long int *seedArray, int numSeeds);
void seedQuESTDefault(void);

/* registers */
Qureg createQureg(int numQubits, QuESTEnv env);
Qureg createDensityQureg(int numQubits, QuESTEnv env);
Qureg createCloneQureg(Qureg qureg, QuESTEnv env);
void destroyQureg(Qureg qureg, QuESTEnv env);
void cloneQureg(Qureg targetQureg, Qureg copyQureg);
int getNumQubits(Qureg qureg);
long long int getNumAmps(Qureg qureg);
void reportQuregParams(Qureg qureg);
void reportState(Qureg qureg);
void reportStateToScreen(Qureg qureg, QuESTEnv env, int reportRank);
void copyStateToGPU(Qureg qureg);
void copyStateFromGPU(Qureg qureg);

/* matrices & operator structs */
ComplexMatrixN createComplexMatrixN(int numQubits);
void destroyComplexMatrixN(ComplexMatrixN matr);
PauliHamil createPauliHamil(int numQubits, int numSumTerms);
void destroyPauliHamil(PauliHamil hamil);
PauliHamil createPauliHamilFromFile(char* fn);
void initPauliHamil(PauliHamil hamil, qreal* coeffs, enum pauliOpType* codes);
void reportPauliHamil(PauliHamil hamil);
DiagonalOp createDiagonalOp(int numQubits, QuESTEnv env);
void destroyDiagonalOp(DiagonalOp op, QuESTEnv env);
void syncDiagonalOp(DiagonalOp op);
void initDiagonalOp(DiagonalOp op, qreal* real, qreal* imag);
void setDiagonalOpElems(DiagonalOp op, long long int startInd,
                        qreal* real, qreal* imag, long long int numElems);

/* state initialisation */
void initBlankState(Qureg qureg);
void initZeroState(Qureg qureg);
void initPlusState(Qureg qureg);
void initClassicalState(Qureg qureg, long long int stateInd);
void initPureState(Qureg qureg, Qureg pure);
void initDebugState(Qureg qureg);
void initStateFromAmps(Qureg qureg, qreal* reals, qreal* imags);
void setAmps(Qureg qureg, long long int startInd, qreal* reals, qreal* imags,
             long long int numAmps);
void setWeightedQureg(Complex fac1, Qureg qureg1, Complex fac2, Qureg qureg2,
                      Complex facOut, Qureg out);

/* QASM logging */
void startRecordingQASM(Qureg qureg);
void stopRecordingQASM(Qureg qureg);
void clearRecordedQASM(Qureg qureg);
void printRecordedQASM(Qureg qureg);
void writeRecordedQASMToFile(Qureg qureg, char* filename);

/* unitaries */
void phaseShift(Qureg qureg, int targetQubit, qreal angle);
void controlledPhaseShift(Qureg qureg, int idQubit1, int idQubit2, qreal angle);
void multiControlledPhaseShift(Qureg qureg, int *controlQubits,
                               int numControlQubits, qreal angle);
void controlledPhaseFlip(Qureg qureg, int idQubit1, int idQubit2);
void multiControlledPhaseFlip(Qureg qureg, int *controlQubits, int numControlQubits);
void sGate(Qureg qureg, int targetQubit);
void tGate(Qureg qureg, int targetQubit);
void unitary(Qureg qureg, int targetQubit, ComplexMatrix2 u);
void compactUnitary(Qureg qureg, int targetQubit, Complex alpha, Complex beta);
void rotateX(Qureg qureg, int rotQubit, qreal angle);
void rotateY(Qureg qureg, int rotQubit, qreal angle);
void rotateZ(Qureg qureg, int rotQubit, qreal angle);
void rotateAroundAxis(Qureg qureg, int rotQubit, qreal angle, Vector axis);
void controlledRotateX(Qureg qureg, int controlQubit, int targetQubit, qreal angle);
void controlledRotateY(Qureg qureg, int controlQubit, int targetQubit, qreal angle);
void controlledRotateZ(Qureg qureg, int controlQubit, int targetQubit, qreal angle);
void controlledRotateAroundAxis(Qureg qureg, int controlQubit, int targetQubit,
                                qreal angle, Vector axis);
void controlledCompactUnitary(Qureg qureg, int controlQubit, int targetQubit,
                              Complex alpha, Complex beta);
void controlledUnitary(Qureg qureg, int controlQubit, int targetQubit,
                       ComplexMatrix2 u);
void multiControlledUnitary(Qureg qureg, int* controlQubits, int numControlQubits,
                            int targetQubit, ComplexMatrix2 u);
void multiStateControlledUnitary(Qureg qureg, int* controlQubits,
                                 int* controlState, int numControlQubits,
                                 int targetQubit, ComplexMatrix2 u);
void pauliX(Qureg qureg, int targetQubit);
void pauliY(Qureg qureg, int targetQubit);
void pauliZ(Qureg qureg, int targetQubit);
void hadamard(Qureg qureg, int targetQubit);
void controlledNot(Qureg qureg, int controlQubit, int targetQubit);
void controlledPauliY(Qureg qureg, int controlQubit, int targetQubit);
void swapGate(Qureg qureg, int qubit1, int qubit2);
void sqrtSwapGate(Qureg qureg, int qb1, int qb2);
void multiRotateZ(Qureg qureg, int* qubits, int numQubits, qreal angle);
void multiRotatePauli(Qureg qureg, int* targetQubits,
                      enum pauliOpType* targetPaulis, int numTargets, qreal angle);
void twoQubitUnitary(Qureg qureg, int targetQubit1, int targetQubit2,
                     ComplexMatrix4 u);
void controlledTwoQubitUnitary(Qureg qureg, int controlQubit, int targetQubit1,
                               int targetQubit2, ComplexMatrix4 u);
void multiControlledTwoQubitUnitary(Qureg qureg, int* controlQubits,
                                    int numControlQubits, int targetQubit1,
                                    int targetQubit2, ComplexMatrix4 u);
void multiQubitUnitary(Qureg qureg, int* targs, int numTargs, ComplexMatrixN u);
void controlledMultiQubitUnitary(Qureg qureg, int ctrl, int* targs, int numTargs,
                                 ComplexMatrixN u);
void multiControlledMultiQubitUnitary(Qureg qureg, int* ctrls, int numCtrls,
                                      int* targs, int numTargs, ComplexMatrixN u);

/* operators (non-unitary application) */
void applyMatrix2(Qureg qureg, int targetQubit, ComplexMatrix2 u);
void applyMatrix4(Qureg qureg, int targetQubit1, int targetQubit2, ComplexMatrix4 u);
void applyMatrixN(Qureg qureg, int* targs, int numTargs, ComplexMatrixN u);
void applyMultiControlledMatrixN(Qureg qureg, int* ctrls, int numCtrls,
                                 int* targs, int numTargs, ComplexMatrixN u);
void applyPauliSum(Qureg inQureg, enum pauliOpType* allPauliCodes,
                   qreal* termCoeffs, int numSumTerms, Qureg outQureg);
void applyPauliHamil(Qureg inQureg, PauliHamil hamil, Qureg outQureg);
void applyTrotterCircuit(Qureg qureg, PauliHamil hamil, qreal time, int order,
                         int reps);
void applyDiagonalOp(Qureg qureg, DiagonalOp op);

/* decoherence */
void mixDephasing(Qureg qureg, int targetQubit, qreal prob);
void mixTwoQubitDephasing(Qureg qureg, int qubit1, int qubit2, qreal prob);
void mixDepolarising(Qureg qureg, int targetQubit, qreal prob);
void mixTwoQubitDepolarising(Qureg qureg, int qubit1, int qubit2, qreal prob);
void mixDamping(Qureg qureg, int targetQubit, qreal prob);
void mixPauli(Qureg qureg, int targetQubit, qreal probX, qreal probY, qreal probZ);
void mixDensityMatrix(Qureg combineQureg, qreal prob, Qureg otherQureg);
void mixKrausMap(Qureg qureg, int target, ComplexMatrix2 *ops, int numOps);
void mixTwoQubitKrausMap(Qureg qureg, int target1, int target2,
                         ComplexMatrix4 *ops, int numOps);
void mixMultiQubitKrausMap(Qureg qureg, int* targets, int numTargets,
                           ComplexMatrixN* ops, int numOps);

/* measurement & calculations */
int measure(Qureg qureg, int measureQubit);
int measureWithStats(Qureg qureg, int measureQubit, qreal *outcomeProb);
qreal collapseToOutcome(Qureg qureg, int measureQubit, int outcome);
qreal calcProbOfOutcome(Qureg qureg, int measureQubit, int outcome);
qreal calcTotalProb(Qureg qureg);
Complex getAmp(Qureg qureg, long long int index);
qreal getRealAmp(Qureg qureg, long long int index);
qreal getImagAmp(Qureg qureg, long long int index);
qreal getProbAmp(Qureg qureg, long long int index);
Complex getDensityAmp(Qureg qureg, long long int row, long long int col);
Complex calcInnerProduct(Qureg bra, Qureg ket);
qreal calcDensityInnerProduct(Qureg rho1, Qureg rho2);
qreal calcPurity(Qureg qureg);
qreal calcFidelity(Qureg qureg, Qureg pureState);
qreal calcHilbertSchmidtDistance(Qureg a, Qureg b);
qreal calcExpecPauliProd(Qureg qureg, int* targetQubits,
                         enum pauliOpType* pauliCodes, int numTargets,
                         Qureg workspace);
qreal calcExpecPauliSum(Qureg qureg, enum pauliOpType* allPauliCodes,
                        qreal* termCoeffs, int numSumTerms, Qureg workspace);
qreal calcExpecPauliHamil(Qureg qureg, PauliHamil hamil, Qureg workspace);
Complex calcExpecDiagonalOp(Qureg qureg, DiagonalOp op);

/* debug API (ref: QuEST_debug.h) */
void initStateDebug(Qureg qureg);
void initStateOfSingleQubit(Qureg *qureg, int qubitId, int outcome);
int initStateFromSingleFile(Qureg *qureg, char filename[200], QuESTEnv env);
void setDensityAmps(Qureg qureg, qreal* reals, qreal* imags);
int compareStates(Qureg mq1, Qureg mq2, qreal precision);
int QuESTPrecision(void);

/* C-only VLA helpers, mirroring the reference's guards (ref: QuEST.h:340,
 * :3859-3916): succinct ComplexMatrixN population from stack 2D arrays. */
#ifndef __cplusplus
void initComplexMatrixN(ComplexMatrixN m, qreal real[][1<<m.numQubits],
                        qreal imag[][1<<m.numQubits]);
ComplexMatrixN bindArraysToStackComplexMatrixN(
    int numQubits, qreal re[][1<<numQubits], qreal im[][1<<numQubits],
    qreal** reStorage, qreal** imStorage);
#define UNPACK_ARR(...) __VA_ARGS__
#define getStaticComplexMatrixN(numQubits, re, im) \
    bindArraysToStackComplexMatrixN( \
        numQubits, \
        (qreal[1<<numQubits][1<<numQubits]) UNPACK_ARR re, \
        (qreal[1<<numQubits][1<<numQubits]) UNPACK_ARR im, \
        (double*[1<<numQubits]) {NULL}, (double*[1<<numQubits]) {NULL} \
    )
#endif

#ifdef __cplusplus
}
#endif

#endif /* QUEST_TPU_C_H */
