/* Complex-scalar shim for the quest_tpu C front-end: adapts the runtime's
 * value-struct Complex to the language's native complex type (C99 _Complex
 * or C++ std::complex), mirroring the reference's QuEST_complex.h contract
 * (qcomp + toComplex/fromComplex) without copying it. */

#ifndef QUEST_TPU_COMPLEX_H
#define QUEST_TPU_COMPLEX_H

#include "quest_tpu_c.h"

#ifdef __cplusplus

#include <cmath>
#include <complex>

typedef std::complex<qreal> qcomp;
/* part of the reference header's contract: user code written against it
 * relies on std names and 3i-style literals being in scope */
using namespace std;
using namespace std::complex_literals;
#define toComplex(scalar) \
    (Complex{static_cast<qreal>(std::real(scalar)), \
             static_cast<qreal>(std::imag(scalar))})
#define fromComplex(comp) qcomp((comp).real, (comp).imag)

#else

#include <math.h>
#include <complex.h>

typedef double _Complex qcomp;
#define toComplex(scalar) ((Complex) {.real = creal(scalar), .imag = cimag(scalar)})
#define fromComplex(comp) ((comp).real + I*((comp).imag))

#endif

#endif /* QUEST_TPU_COMPLEX_H */
