#!/bin/sh
# Build libquest_tpu_c.so — the QuEST-compatible C front-end over the
# quest_tpu Python/JAX runtime.
set -e
cd "$(dirname "$0")"
mkdir -p build
CFLAGS="$(python3-config --includes)"
LDFLAGS="$(python3-config --ldflags --embed)"
g++ -O2 -std=c++17 -shared -fPIC quest_shim.cpp -o build/libquest_tpu_c.so \
    $CFLAGS $LDFLAGS
echo "built native/capi/build/libquest_tpu_c.so"
