// Standalone self-test for the gate-fusion engine, built with
// -fsanitize=address,undefined in CI (the native analogue of the
// reference's QUEST_MEMCHECK clang-ASan build, ref:
// QuEST/CMakeLists.txt:347-360, .github/workflows/llvm-asan.yml).
//
// Exercises the full C ABI surface — parse, peephole passes, kron packing,
// serialise, free — on handcrafted streams including adversarial ones
// (truncated buffers, zero gates, wide diagonals), so leaks and
// out-of-bounds accesses in the optimizer surface here rather than under
// the Python runtime.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

extern "C" {
uint8_t* quest_fuse_circuit(const uint8_t* buf, int64_t len, int64_t* out_len,
                            int32_t max_pack);
void quest_free_buffer(uint8_t* buf);
int64_t quest_fusion_abi_version();
}

namespace {

struct GateSpec {
    int32_t kind;
    std::vector<int32_t> targets;
    std::vector<int32_t> controls;
    std::vector<double> payload;
};

std::vector<uint8_t> pack(const std::vector<GateSpec>& gates) {
    std::vector<uint8_t> out;
    auto put = [&](const void* p, size_t n) {
        const uint8_t* b = static_cast<const uint8_t*>(p);
        out.insert(out.end(), b, b + n);
    };
    int64_t n = static_cast<int64_t>(gates.size());
    put(&n, 8);
    for (const GateSpec& g : gates) {
        int32_t nt = static_cast<int32_t>(g.targets.size());
        int32_t nc = static_cast<int32_t>(g.controls.size());
        int64_t pl = static_cast<int64_t>(g.payload.size());
        put(&g.kind, 4);
        put(&nt, 4);
        put(&nc, 4);
        put(&pl, 8);
        put(g.targets.data(), 4 * nt);
        put(g.controls.data(), 4 * nc);
        std::vector<int32_t> states(nc, 1);
        put(states.data(), 4 * nc);
        put(g.payload.data(), 8 * pl);
    }
    return out;
}

int64_t count_gates(const uint8_t* buf) {
    int64_t n;
    std::memcpy(&n, buf, 8);
    return n;
}

GateSpec h(int q) {
    double s = 0.70710678118654752;
    return {0, {q}, {}, {s, s, s, -s, 0, 0, 0, 0}};
}

GateSpec x(int q) { return {2, {q}, {}, {}}; }
GateSpec z(int q) { return {1, {q}, {}, {1, -1, 0, 0}}; }
GateSpec cz(int c, int q) { return {1, {q}, {c}, {1, -1, 0, 0}}; }
GateSpec swap_g(int a, int b) { return {5, {a, b}, {}, {}}; }

int check(const char* name, const std::vector<GateSpec>& in, int32_t max_pack,
          int64_t want_gates) {
    std::vector<uint8_t> buf = pack(in);
    int64_t out_len = 0;
    uint8_t* out = quest_fuse_circuit(buf.data(),
                                      static_cast<int64_t>(buf.size()),
                                      &out_len, max_pack);
    int64_t got = count_gates(out);
    quest_free_buffer(out);
    if (want_gates >= 0 && got != want_gates) {
        std::printf("FAIL %s: %lld gates, want %lld\n", name,
                    static_cast<long long>(got),
                    static_cast<long long>(want_gates));
        return 1;
    }
    std::printf("ok %s (%lld gates)\n", name, static_cast<long long>(got));
    return 0;
}

}  // namespace

int main() {
    int fails = 0;
    if (quest_fusion_abi_version() != 3) {
        std::printf("FAIL abi version\n");
        return 1;
    }
    fails += check("empty", {}, 7, 0);
    fails += check("hh-cancel", {h(0), h(0)}, 1, 0);
    fails += check("xx-cancel", {x(0), x(0)}, 1, 0);
    fails += check("swap-swap-cancel", {swap_g(0, 1), swap_g(0, 1)}, 1, 0);
    fails += check("zz-merge", {z(0), z(0)}, 1, 0);  // z*z = identity
    fails += check("pack-layer", {h(0), h(1), h(2), h(3)}, 7, 1);
    fails += check("pack-with-diag", {x(0), h(1), z(2), z(3)}, 7, 1);
    fails += check("cz-absorb", {h(0), h(1), cz(0, 1)}, 7, 1);
    fails += check("ctrl-blocks-pack", {h(0), cz(1, 2), h(3)}, 2, 2);
    // wide diagonal: 16-qubit controlled phase absorbs controls (kDiagCap)
    {
        std::vector<GateSpec> wide;
        GateSpec g = z(0);
        for (int c = 1; c < 16; c++) g.controls.push_back(c);
        wide.push_back(g);
        fails += check("wide-ctrl-diag", wide, 7, 1);
    }
    // 200-gate random-ish stream: stresses repeated passes + reallocation
    {
        std::vector<GateSpec> big;
        for (int i = 0; i < 200; i++) {
            int q = (i * 7) % 10;
            if (i % 3 == 0) big.push_back(h(q));
            else if (i % 3 == 1) big.push_back(z(q));
            else big.push_back(cz(q, (q + 1) % 10));
        }
        fails += check("large-stream", big, 7, -1);
    }
    if (fails) {
        std::printf("%d failures\n", fails);
        return 1;
    }
    std::printf("all fusion self-tests passed\n");
    return 0;
}
