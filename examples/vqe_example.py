"""Variational simulation on the differentiable layer: VQE and QAOA.

No reference analogue — QuEST has no gradient capability; this is the
TPU-native extension (quest_tpu/autodiff.py).  The whole objective
(state prep -> parametric circuit -> Pauli-sum expectation) is ONE jitted
XLA program; jax.value_and_grad adds the adjoint pass, and jax.vmap runs a
multi-start batch of optimisations in parallel on the MXU.

Run:  python examples/vqe_example.py
"""

import os

# CPU is fine for this demo scale; set QUEST_EXAMPLE_PLATFORM=tpu (or any
# registered platform) to run on an accelerator instead.
os.environ["JAX_PLATFORMS"] = os.environ.get("QUEST_EXAMPLE_PLATFORM", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
import optax

import quest_tpu as qt
from quest_tpu.models import (hardware_efficient_ansatz, maxcut_hamiltonian,
                              pauli_sum_matrix, qaoa_maxcut_circuit,
                              tfim_hamiltonian)


def vqe_tfim():
    """Ground state of the 6-qubit critical transverse-field Ising chain."""
    n = 6
    hamil = tfim_hamiltonian(n, field=1.0)
    ansatz = hardware_efficient_ansatz(n, layers=4)
    energy = qt.expectation_fn(ansatz, hamil)
    value_and_grad = jax.jit(jax.value_and_grad(energy))

    # batched multi-start: 8 random initialisations optimised IN PARALLEL —
    # one vmapped update step, every start on the device at once
    starts = 8
    params = jnp.asarray(np.random.default_rng(0).normal(
        0, 0.1, (starts, ansatz.num_params)))
    opt = optax.adam(0.1)
    opt_state = jax.vmap(opt.init)(params)

    @jax.jit
    def step(params, opt_state):
        def one(p, s):
            v, g = jax.value_and_grad(energy)(p)
            up, s = opt.update(g, s)
            return optax.apply_updates(p, up), s, v
        return jax.vmap(one)(params, opt_state)

    for it in range(300):
        params, opt_state, vals = step(params, opt_state)
        if it % 50 == 0:
            print(f"  iter {it:3d}: best E = {float(jnp.min(vals)):+.6f}")

    exact = np.linalg.eigvalsh(pauli_sum_matrix(hamil))[0]
    best = float(jnp.min(vals))
    print(f"  VQE best of {starts} starts: {best:+.6f}   exact: {exact:+.6f}")
    # single value_and_grad call for the winner (energy + full gradient in
    # one forward+adjoint program)
    winner = params[int(jnp.argmin(vals))]
    v, g = value_and_grad(winner)
    print(f"  winner gradient norm: {float(jnp.linalg.norm(g)):.2e}")


def qaoa_ring():
    """MaxCut of the 8-cycle with depth-3 QAOA (optimum cut = 8)."""
    n = 8
    edges = [(i, (i + 1) % n) for i in range(n)]
    circuit = qaoa_maxcut_circuit(n, edges, p=3)
    hamil = maxcut_hamiltonian(n, edges)
    energy = qt.expectation_fn(circuit, hamil)
    value_and_grad = jax.jit(jax.value_and_grad(energy))

    params = jnp.full(circuit.num_params, 0.1)
    opt = optax.adam(0.05)
    opt_state = opt.init(params)
    for it in range(300):
        v, g = value_and_grad(params)
        updates, opt_state = opt.update(g, opt_state)
        params = optax.apply_updates(params, updates)
    print(f"  QAOA p=3 energy: {float(v):+.4f}  (optimal cut 8 -> energy -8)")
    print(f"  expected cut size: {-float(v):.3f} / 8")


def trainable_noise():
    """Gradients through channel probabilities: fit a damping rate so the
    noisy GHZ state matches a target purity."""
    n = 3
    circuit = qt.ParamCircuit(n)
    rate = circuit.param()
    circuit.h(0).cnot(0, 1).cnot(1, 2)
    for q in range(n):
        circuit.damp(q, rate)
    run = qt.build_param_circuit(circuit, density=True)

    target_purity = 0.6

    @jax.jit
    def loss(p):
        rho0 = jnp.zeros((2, 1 << (2 * n))).at[0, 0].set(1.0)
        rho = run(p, rho0)
        purity = jnp.sum(rho[0] ** 2 + rho[1] ** 2)
        return (purity - target_purity) ** 2

    grad_fn = jax.jit(jax.grad(loss))
    p = jnp.asarray([0.05])
    opt = optax.adam(0.02)
    st = opt.init(p)
    for _ in range(200):
        g = grad_fn(p)
        up, st = opt.update(g, st)
        p = optax.apply_updates(p, up)
    print(f"  fitted damping rate: {float(p[0]):.4f}  "
          f"(loss {float(loss(p)):.2e})")


def adjoint_method():
    """O(1)-memory gradients: a 14-qubit, 112-parameter ansatz differentiated
    by uncomputing through gate inverses (three live statevectors at any
    depth — on a TPU this scales to 27 qubits, where taped reverse-mode
    cannot run at all)."""
    from quest_tpu.autodiff import adjoint_gradient_fn

    n = 14
    ansatz = hardware_efficient_ansatz(n, layers=3)
    hamil = tfim_hamiltonian(n)
    fn = adjoint_gradient_fn(ansatz, hamil)
    params = jnp.asarray(np.random.default_rng(3).normal(0, 0.1, ansatz.num_params))
    energy, grad = fn(params)
    v0, g0 = jax.value_and_grad(qt.expectation_fn(ansatz, hamil))(params)
    print(f"  {ansatz.num_params} params: E = {float(energy):+.6f}  "
          f"(taped reverse-mode agrees to "
          f"{float(jnp.max(jnp.abs(grad - g0))):.1e})")


if __name__ == "__main__":
    print("VQE: 6-qubit critical TFIM, 8 parallel starts (vmap)")
    vqe_tfim()
    print("QAOA: MaxCut on the 8-cycle")
    qaoa_ring()
    print("Trainable noise: fitting a damping rate by gradient descent")
    trainable_noise()
    print("Adjoint method: taping-free full gradient of a 14-qubit ansatz")
    adjoint_method()
