#!/bin/bash
# Run the unit-test suite against a real multi-host TPU pod — the analogue of
# the reference's distributed test submission
# (ref: examples/submissionScripts/mpi_SLURM_unit_tests.sh, which reruns the
# whole Catch2 suite under 4 MPI ranks).
#
# The suite's dist8 parametrisation normally shards over 8 VIRTUAL CPU
# devices; on a pod host the same tests run with the env built over the
# pod's real chips (QUEST_TEST_PLATFORM=tpu).  Accelerator-precision
# tolerances apply (precision 1), exactly as the reference's GPU test run
# loosens its own tolerances.
#
# Usage:
#   TPU_NAME=my-v5e-pod ZONE=us-west4-a ./tpu_pod_unit_tests.sh
#
# The 2-process distribution properties (jax.distributed.initialize,
# multi-host checkpointing) are also covered hermetically on any machine by:
#   python -m pytest tests/test_multihost.py -q

set -euo pipefail

: "${TPU_NAME:?set TPU_NAME to the pod slice name}"
: "${ZONE:?set ZONE to the pod's GCE zone}"
REPO_DIR=${REPO_DIR:-$(cd "$(dirname "$0")/../.." && pwd)}

gcloud compute tpus tpu-vm scp --recurse "$REPO_DIR" "$TPU_NAME":~/quest-tpu \
    --zone "$ZONE" --worker=all
gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
    --command='cd ~/quest-tpu && QUEST_TEST_PLATFORM=tpu python -m pytest tests/ -x -q'
