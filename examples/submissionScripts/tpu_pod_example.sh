#!/bin/bash
# Launch the multi-host example on every host of a TPU pod slice — the
# TPU-native analogue of the reference's mpirun submission script
# (ref: examples/submissionScripts/mpi_SLURM_example.sh: 4 nodes x 1 MPI
# rank x 8 OMP threads).  On TPU there is no mpirun: the pod launcher runs
# the SAME Python program on every host, and jax.distributed.initialize()
# (called inside the program) plays MPI_Init, discovering the coordinator
# from the TPU runtime.
#
# Usage (from a machine with gcloud configured):
#   TPU_NAME=my-v5e-pod ZONE=us-west4-a ./tpu_pod_example.sh
#
# No pod at hand? Rehearse the identical code path locally:
#   python ../multihost_example.py --rehearse

set -euo pipefail

: "${TPU_NAME:?set TPU_NAME to the pod slice name}"
: "${ZONE:?set ZONE to the pod's GCE zone}"
REPO_DIR=${REPO_DIR:-$(cd "$(dirname "$0")/../.." && pwd)}

# Ship the framework to every host, then run the example everywhere.
gcloud compute tpus tpu-vm scp --recurse "$REPO_DIR" "$TPU_NAME":~/quest-tpu \
    --zone "$ZONE" --worker=all
gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
    --command='cd ~/quest-tpu && PYTHONPATH=. python examples/multihost_example.py'
