"""Importable circuit factories for the examples — and for the analyzer.

The example scripts build their circuits inline (and run them); this
module exposes the same circuits as zero-argument factories so
``python -m quest_tpu.analysis --circuit circuits:NAME`` can analyze,
schedule and translation-validate them without executing a simulation —
CI's ``--verify-schedule`` smoke runs every factory here.
"""

from __future__ import annotations

from quest_tpu.circuit import Circuit, qft_circuit


def distributed_qft() -> Circuit:
    """The circuit of examples/distributed_qft.py: a 16-qubit QFT (fused by
    the native engine when available), scheduled over the 8-device mesh by
    the example itself."""
    return qft_circuit(16).optimize()


def bernstein_vazirani(num_qubits: int = 16, secret: int = 2 ** 4 + 1) -> Circuit:
    """The circuit of examples/bernstein_vazirani_circuit.py as a recorded
    Circuit: ancilla flip + one CNOT per secret bit.  The example script
    runs 9 qubits; the factory defaults to 16 so the CI mesh smoke
    analyzes a deployment-sized register (a 9-qubit state over 8 devices
    is 64 amps per shard — smaller than one 128-wide lane row, the layout
    regime where the planner now charges every dense gate the 'subtile'
    comm class and the analyzer warns ``A_SUBTILE_SHARD``; see
    planner.sub_tile_shard — promoted from a found-by-audit note here to
    a modeled comm class)."""
    c = Circuit(num_qubits)
    c.x(0)
    bits = secret
    for qb in range(1, num_qubits):
        bit, bits = bits % 2, bits // 2
        if bit:
            c.cnot(0, qb)
    return c
