"""Importable circuit factories for the examples — and for the analyzer.

The example scripts build their circuits inline (and run them); this
module exposes the same circuits as zero-argument factories so
``python -m quest_tpu.analysis --circuit circuits:NAME`` can analyze,
schedule and translation-validate them without executing a simulation —
CI's ``--verify-schedule`` smoke runs every factory here.
"""

from __future__ import annotations

from quest_tpu.circuit import Circuit, DensityCircuit, qft_circuit


def _haar(rng, k: int = 1):
    """One Haar-random 2^k x 2^k unitary (the QR sampler every factory
    here shares — phase-normalized so the distribution is exactly Haar)."""
    import numpy as np
    d = 1 << k
    g = rng.normal(size=(d, d)) + 1j * rng.normal(size=(d, d))
    u, r = np.linalg.qr(g)
    return u * (np.diag(r) / np.abs(np.diag(r)))


def distributed_qft() -> Circuit:
    """The circuit of examples/distributed_qft.py: a 16-qubit QFT (fused by
    the native engine when available), scheduled over the 8-device mesh by
    the example itself."""
    return qft_circuit(16).optimize()


def bernstein_vazirani(num_qubits: int = 16, secret: int = 2 ** 4 + 1) -> Circuit:
    """The circuit of examples/bernstein_vazirani_circuit.py as a recorded
    Circuit: ancilla flip + one CNOT per secret bit.  The example script
    runs 9 qubits; the factory defaults to 16 so the CI mesh smoke
    analyzes a deployment-sized register (a 9-qubit state over 8 devices
    is 64 amps per shard — smaller than one 128-wide lane row, the layout
    regime where the planner now charges every dense gate the 'subtile'
    comm class and the analyzer warns ``A_SUBTILE_SHARD``; see
    planner.sub_tile_shard — promoted from a found-by-audit note here to
    a modeled comm class)."""
    c = Circuit(num_qubits)
    c.x(0)
    bits = secret
    for qb in range(1, num_qubits):
        bit, bits = bits % 2, bits // 2
        if bit:
            c.cnot(0, qb)
    return c


def mixed_envelope_16q() -> Circuit:
    """A 16-qubit mixed window exercising the epoch executor's WIDENED
    envelope (docs/SCHEDULER.md par.6): the degenerate single-block
    geometry (n < 17: the whole state is one VMEM tile), cross-group 2q
    dense gates lowered by the odd-bit block decomposition (targets
    straddling the lane/sublane/fiber axis groups), controlled dense and
    diagonal ops, and a swap absorbed by the deferred qubit map.  CI's
    ``--verify-schedule --engine pallas`` step proves the lowering
    IR-equivalent and probes the actual kernels in interpret mode."""
    import numpy as np
    rng = np.random.default_rng(16)
    c = Circuit(16)
    c.h(0)
    c.multi_qubit_unitary((3, 12), _haar(rng, 2))  # lane x fiber: decomposed
    c.multi_qubit_unitary((8, 14), _haar(rng, 2))  # sublane x fiber
    c.multi_qubit_unitary((5,), _haar(rng), controls=(11,))
    c.cz(2, 9)
    c.multi_rotate_z((0, 4, 8, 12), 0.61)       # unlifted-ok: fixed demo angle
    c.swap(1, 13)                                # deferred: zero passes
    c.unitary(1, _haar(rng))
    # unlifted-ok: fixed demo angle — this showcase class compiles once
    c.phase_shift(15, 0.37, controls=(6,))
    return c


def density_noise_9q() -> DensityCircuit:
    """A 9-qubit NOISY density-matrix circuit (Choi-doubled: an 18-qubit
    register — full block geometry, pack passes included) exercising the
    epoch executor's fused superoperator lowering (docs/SCHEDULER.md §6
    density rows): two mixed layers of Haar 1q gates (each recorded with
    its conjugate bra-side shadow) followed by amplitude damping,
    depolarising, dephasing and a general 1-qubit Kraus channel — the
    channels whose doubled pair (q, q+9) straddles the block/pack split
    lower as widened-column pack superoperator stages, the rest as
    block superoperator/dense stages.  CI's density verify-schedule step
    proves the Choi-doubling against the Kraus oracle
    (``check_density_lowering``), the fused plan IR-equivalent
    (``check_epoch_plan``) and the actual kernels in interpret mode —
    with ZERO V_* findings and zero XLA-fallback ops."""
    import numpy as np
    rng = np.random.default_rng(9)
    n = 9
    c = DensityCircuit(n)
    for layer in range(2):
        for q in range(n):
            c.unitary(q, _haar(rng))
        for q in range(layer, n, 2):
            c.damp(q, 0.02 + 0.01 * layer)
        for q in range(1 - layer, n, 2):
            c.depolarise(q, 0.015)      # unlifted-ok: fixed demo noise model
    c.dephase(4, 0.08)                  # unlifted-ok: fixed demo noise model
    c.two_qubit_dephase(0, 5, 0.06)     # unlifted-ok: fixed demo noise model
    c.kraus((8,), [np.diag([1.0, np.sqrt(0.85)]),
                   np.array([[0.0, np.sqrt(0.15)], [0.0, 0.0]])])
    return c
