"""Single-qubit amplitude damping on a density matrix (ref analogue:
examples/damping_example.c)."""

import quest_tpu as qt

env = qt.createQuESTEnv()

print("-------------------------------------------------------")
print("Running quest_tpu damping example:\n\t Basic circuit involving damping of a qubit.")
print("-------------------------------------------------------")

qubits = qt.createDensityQureg(1, env)
qt.initPlusState(qubits)

print("\n Reporting the qubit state to screen:")
qt.reportStateToScreen(qubits, env, 0)

print("\n Applying damping 10 times with probability 0.1")
for counter in range(10):
    qt.mixDamping(qubits, 0, 0.1)
    print(f"\n Qubit state after applying damping {counter + 1} times:")
    qt.reportStateToScreen(qubits, env, 0)

qt.destroyQureg(qubits, env)
qt.destroyQuESTEnv(env)
