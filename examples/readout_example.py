"""Multi-shot readout, joint distributions, and subsystem analysis.

TPU-native extensions (no analogue in the v3.2 reference, which reads one
qubit at a time): calcProbOfAllOutcomes computes a joint outcome
distribution in one fused device pass, sampleOutcomes draws shots without
collapsing the state, and calcPartialTrace / calcVonNeumannEntropy analyse
any subsystem.

Run:  PYTHONPATH=. python examples/readout_example.py
"""

import os

os.environ["JAX_PLATFORMS"] = os.environ.get("QUEST_EXAMPLE_PLATFORM", "cpu")

import numpy as np

import quest_tpu as qt


def main():
    env = qt.createQuESTEnv(1)
    n = 5
    psi = qt.createQureg(n, env)

    # a GHZ state plus a rotated spectator qubit
    qt.hadamard(psi, 0)
    for q in range(3):
        qt.controlledNot(psi, q, q + 1)
    qt.rotateY(psi, 4, 0.9)

    # joint distribution of the GHZ core: only |0000> and |1111>
    probs = qt.calcProbOfAllOutcomes(psi, [0, 1, 2, 3])
    print("GHZ core outcomes with nonzero probability:")
    for o in np.nonzero(probs > 1e-12)[0]:
        print(f"  |{o:04b}>  p = {probs[o]:.4f}")

    # 10000 shots, reproducible from the seeded MT19937 stream, and the
    # state is NOT collapsed
    qt.seedQuEST([2026])
    shots = qt.sampleOutcomes(psi, 10000, [0, 1, 2, 3])
    counts = np.bincount(shots, minlength=16)
    print(f"10000 shots: {counts[0]} x |0000>, {counts[15]} x |1111>")
    print(f"state intact: total probability {qt.calcTotalProb(psi):.6f}")

    # subsystem analysis: half the GHZ core carries exactly 1 bit of
    # entanglement entropy; the spectator is in a pure state (0 bits)
    print(f"S(qubits 0,1)   = {qt.calcVonNeumannEntropy(psi, [0, 1]):.6f} bits")
    print(f"S(spectator 4)  = {qt.calcVonNeumannEntropy(psi, [4]):.6f} bits")

    # the reduced density matrix of the spectator is the rotated pure state
    red = qt.calcPartialTrace(psi, [0, 1, 2, 3])
    c, s = np.cos(0.45), np.sin(0.45)
    print("spectator reduced matrix (expect [[c^2, cs], [cs, s^2]]):")
    print(np.array([[qt.getDensityAmp(red, r, cc).real for cc in range(2)]
                    for r in range(2)]).round(6))
    assert abs(qt.getDensityAmp(red, 0, 0).real - c * c) < 1e-10


if __name__ == "__main__":
    main()
