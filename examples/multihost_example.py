"""Multi-host distributed simulation — the TPU-pod analogue of running the
reference under ``mpirun`` (ref: examples/submissionScripts/mpi_SLURM_example.sh,
QuEST_cpu_distributed.c:129-160 MPI_Init + rank discovery).

On a TPU pod slice, run this SAME file on every host (see
``submissionScripts/tpu_pod_example.sh``).  ``jax.distributed.initialize()``
plays the role of ``MPI_Init``: every process contributes its local chips to
one global mesh, and the single-controller SPMD program below is compiled
once and executed across all of them — XLA inserts the ICI/DCN collectives
that the reference hand-wrote as MPI_Sendrecv/Allreduce.

Run modes:

  python multihost_example.py                 # single host, all local devices
  python multihost_example.py --rehearse      # 2-process rehearsal on CPU
                                              # (no pod needed; same code path)

On a pod, JAX's TPU runtime discovers the coordinator automatically, so
``jax.distributed.initialize()`` needs no arguments; the rehearsal passes
them explicitly.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def simulate() -> None:
    import jax

    import quest_tpu as qt

    nproc = jax.process_count()
    # One env over EVERY device of every host: the mesh is the pod.
    env = qt.createQuESTEnv()
    n = 24 if jax.devices()[0].platform == "tpu" else 12

    q = qt.createQureg(n, env)
    qt.initZeroState(q)

    # GHZ preparation: H then a CNOT ladder crossing every shard boundary.
    qt.hadamard(q, 0)
    for t in range(1, n):
        qt.controlledNot(q, 0, t)

    # Global reductions ride psum over the mesh (ref: MPI_Allreduce).
    total = qt.calcTotalProb(q)
    p_top = qt.calcProbOfOutcome(q, n - 1, 1)

    # Collapse the top (sharded) qubit and verify the GHZ correlation.
    outcome = qt.measure(q, n - 1)
    p_bottom = qt.calcProbOfOutcome(q, 0, outcome)

    if jax.process_index() == 0:
        print(f"processes={nproc} devices={len(jax.devices())} "
              f"local_devices={len(jax.local_devices())}")
        print(qt.getEnvironmentString(env, q))
        print(f"GHZ({n}): totalProb={total:.12f} P(top=1)={p_top:.6f}")
        print(f"measured top={outcome}; P(bottom={outcome})={p_bottom:.6f}")
        assert abs(total - 1.0) < 1e-6
        assert abs(p_top - 0.5) < 1e-6
        assert abs(p_bottom - 1.0) < 1e-6  # perfectly correlated
        print("OK")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rehearse", action="store_true",
                    help="launch a 2-process CPU rehearsal of the pod run")
    ap.add_argument("--worker", type=int, default=None, help=argparse.SUPPRESS)
    ap.add_argument("--port", type=int, default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.rehearse:
        # Re-exec this file twice, as a pod launcher would start it on two
        # hosts; each worker contributes 4 virtual CPU devices.
        import socket
        import subprocess

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [
            subprocess.Popen([sys.executable, os.path.abspath(__file__),
                              "--worker", str(pid), "--port", str(port)])
            for pid in (0, 1)
        ]
        rcs = [p.wait() for p in procs]
        sys.exit(max(rcs))

    if args.worker is not None:
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.distributed.initialize(
            coordinator_address=f"127.0.0.1:{args.port}",
            num_processes=2, process_id=args.worker)
    else:
        import jax
        # On a TPU pod slice the runtime knows the cluster topology, so
        # initialize() needs no arguments; on other clusters the standard
        # coordinator env vars select the explicit spec.  A plain single-host
        # run (neither hint present) skips initialization entirely.
        if ("TPU_WORKER_HOSTNAMES" in os.environ
                or "COORDINATOR_ADDRESS" in os.environ):
            jax.distributed.initialize()

    simulate()


if __name__ == "__main__":
    main()
