"""Bernstein–Vazirani circuit (ref analogue:
examples/bernstein_vazirani_circuit.c) — recovers a secret bitstring with one
oracle query."""

import quest_tpu as qt

num_qubits = 9
secret_num = 2 ** 4 + 1

env = qt.createQuESTEnv()
qureg = qt.createQureg(num_qubits, env)
qt.initZeroState(qureg)

# NOT the ancilla (qubit 0)
qt.pauliX(qureg, 0)

# CNOT secret bits with the ancilla
bits = secret_num
for qb in range(1, num_qubits):
    bit, bits = bits % 2, bits // 2
    if bit:
        qt.controlledNot(qureg, 0, qb)

# probability of reading out the secret string
success_prob = 1.0
bits = secret_num
for qb in range(1, num_qubits):
    bit, bits = bits % 2, bits // 2
    success_prob *= qt.calcProbOfOutcome(qureg, qb, bit)

print(f"probability of successfully determining the secret number: {success_prob:g}")

qt.destroyQureg(qureg, env)
qt.destroyQuESTEnv(env)
