"""TPU-native distributed simulation: a QFT over a multi-device mesh.

No analogue exists in the reference's examples — its distribution is an
invisible build-time property (MPI backend + mpirun).  Here the mesh is an
explicit object, the per-gate communication plan is inspectable BEFORE
compiling, and the same compiled program runs on 1 device or N.

By default this simulates the mesh with 8 virtual CPU devices, so it runs
anywhere; on a machine with a real multi-accelerator mesh set
QUEST_EXAMPLE_REAL_MESH=1 to use it.
"""

import os

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()

import jax

if os.environ.get("QUEST_EXAMPLE_REAL_MESH") != "1":
    # must happen before any backend use — probing jax.devices() first would
    # initialise and pin the default backend
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

from quest_tpu.circuit import apply_circuit, qft_circuit
from quest_tpu.parallel import comm_plan
from quest_tpu.parallel.mesh import amp_sharding, make_amps_mesh
import quest_tpu as qt

N = 16

devices = jax.devices()
if len(devices) & (len(devices) - 1):
    devices = devices[:1 << (len(devices).bit_length() - 1)]
mesh = make_amps_mesh(devices)
sharding = amp_sharding(mesh)
print(f"mesh: {len(devices)} x {devices[0].platform} devices, "
      f"amplitude axis sharded in contiguous chunks")

# the static communication plan — the reference's per-gate MPI decision
# procedure (halfMatrixBlockFitsInChunk / exchange / swap-reroute), made
# inspectable before compiling anything
circuit = qft_circuit(N).optimize()
plans = comm_plan(circuit, len(devices))
moved = sum(p.bytes_moved for p in plans)
kinds = {}
for p in plans:
    kinds[p.comm] = kinds.get(p.comm, 0) + 1
print(f"plan: {len(plans)} fused ops -> {kinds}, "
      f"{moved / 1024:.0f} KiB/device predicted exchange volume")

# the comm-aware scheduler consumes that plan and rewrites the circuit:
# the QFT's trailing bit-reversal swaps fuse into one collective
# (docs/SCHEDULER.md); the scheduled circuit is exactly equivalent
circuit = circuit.schedule(len(devices))
after = sum(p.bytes_moved for p in comm_plan(circuit, len(devices)))
print(f"scheduled: {len(circuit.ops)} ops, predicted exchange volume "
      f"{moved / 1024:.0f} -> {after / 1024:.0f} KiB/device")

# build a sharded Qureg and run the circuit as ONE compiled program; GSPMD
# inserts exactly the collectives the plan predicts
env = qt.createQuESTEnv()
q = qt.createQureg(N, env, dtype=jnp.float32)
qt.initPlusState(q)
q.amps = jax.device_put(q.amps, sharding)

apply_circuit(q, circuit)

# |+...+> is the QFT of |0...0> up to the bit reversal, so the result
# concentrates on |0>: check the probability across all shards (psum)
p0 = qt.calcProbOfOutcome(q, 0, 0)
print(f"total probability {qt.calcTotalProb(q):.6f}, "
      f"P(qubit 0 = 0) = {p0:.6f}")
amp0 = qt.getAmp(q, 0)
print(f"amplitude of |0...0>: {amp0.real:+.6f} {amp0.imag:+.6f}i")
