"""The 3-qubit tutorial circuit (ref analogue: examples/tutorial_example.c).

Output is bit-identical to the reference binary at float64 — the verification
anchor for the framework (BASELINE.md config 1)."""

import quest_tpu as qt

env = qt.createQuESTEnv()

print("-------------------------------------------------------")
print("Running quest_tpu tutorial:\n\t Basic circuit involving a system of 3 qubits.")
print("-------------------------------------------------------")

qubits = qt.createQureg(3, env)
qt.initZeroState(qubits)

print("\nThis is our environment:")
qt.reportQuregParams(qubits)
qt.reportQuESTEnv(env)

# apply circuit (ref: tutorial_example.c:49-82)
qt.hadamard(qubits, 0)
qt.controlledNot(qubits, 0, 1)
qt.rotateY(qubits, 2, 0.1)

qt.multiControlledPhaseFlip(qubits, [0, 1, 2], 3)

u = qt.ComplexMatrix2(real=[[0.5, 0.5], [0.5, 0.5]],
                      imag=[[0.5, -0.5], [-0.5, 0.5]])
qt.unitary(qubits, 0, u)

a = qt.Complex(0.5, 0.5)
b = qt.Complex(0.5, -0.5)
qt.compactUnitary(qubits, 1, a, b)

v = qt.Vector(1, 0, 0)
qt.rotateAroundAxis(qubits, 2, 3.14 / 2, v)

qt.controlledCompactUnitary(qubits, 0, 1, a, b)
qt.multiControlledUnitary(qubits, [0, 1], 2, 2, u)

toff = qt.createComplexMatrixN(3)
toff[6, 7] = 1
toff[7, 6] = 1
for i in range(6):
    toff[i, i] = 1
qt.multiQubitUnitary(qubits, [0, 1, 2], 3, toff)

# study the output state
print("\nCircuit output:")
print(f"Probability amplitude of |111>: {qt.getProbAmp(qubits, 7):g}")
print(f"Probability of qubit 2 being in state 1: {qt.calcProbOfOutcome(qubits, 2, 1):g}")
outcome = qt.measure(qubits, 0)
print(f"Qubit 0 was measured in state {outcome}")
outcome, prob = qt.measureWithStats(qubits, 2)
print(f"Qubit 2 collapsed to {outcome} with probability {prob:g}")

qt.destroyQureg(qubits, env)
qt.destroyQuESTEnv(env)
